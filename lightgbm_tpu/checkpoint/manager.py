"""CheckpointManager: atomic, discoverable, retained checkpoint files.

Write protocol (crash-safe at every step):
1. serialize the TrainState to ``<dir>/ckpt_<iter>.lgbckpt.tmp``
2. rename it over ``<dir>/ckpt_<iter>.lgbckpt`` (os.replace locally; a
   registered io/file_io scheme supplies its own atomic rename)
3. rewrite ``<dir>/MANIFEST.json`` the same tmp+rename way
4. prune to the newest ``keep`` checkpoints

A reader therefore never observes a partial checkpoint: either the rename
happened (file is complete) or it didn't (file is absent).  ``latest()``
unions the manifest with a directory scan so a crash between steps 2 and
3 still finds the newly committed file.

Distributed policy (reference SURVEY §5 checkpoint-restart):
- WRITES are rank-0-only (``save`` is a silent no-op elsewhere): every
  rank trains the same global model under synchronous SPMD, so one copy
  suffices and concurrent writers would race the manifest.
- RESTORES happen on every rank, followed by ``restore_barrier`` — an
  allgather of the restored iteration that both synchronizes the ranks
  and hard-fails if any rank loaded a different checkpoint (possible
  when checkpoint_dir is not actually shared storage).
"""

from __future__ import annotations

import re
import time
from typing import List, Optional, Tuple

import numpy as np

from ..io import file_io
from ..log import LightGBMError, log_info, log_warning
from ..timer import timed
from .state import CheckpointCorruptError, TrainState

__all__ = ["CheckpointManager", "restore_barrier", "atomic_write_text",
           "atomic_write_bytes", "CHECKPOINT_SUFFIX"]

CHECKPOINT_SUFFIX = ".lgbckpt"
_NAME_RE = re.compile(r"^(?P<prefix>.+)_(?P<iter>\d{8})" +
                      re.escape(CHECKPOINT_SUFFIX) + "$")


def _cleanup_tmp(tmp: str) -> None:
    """Best-effort removal of a failed write's tmp file: a torn write
    must not leave ``.tmp`` litter for operators to mistake for data
    (the commit rename never ran, so the target is untouched either
    way)."""
    try:
        file_io.remove(tmp)
    except OSError:
        pass


def _atomic_write(path: str, data, binary: bool) -> None:
    """tmp + rename through the scheme registry, retried as ONE unit on
    transient backend errors (re-running a half-done tmp write is safe by
    construction — the tmp is overwritten, the rename never happened)."""
    tmp = path + ".tmp"

    def _do():
        # the UNRETRIED primitives (_open/_rename_once): the composite
        # owns the single retry layer — open_writable/rename retry
        # internally too, and nesting them under with_retry would square
        # the configured attempt budget
        try:
            with file_io._open(tmp, "wb" if binary else "w") as fh:
                fh.write(data)
            file_io._rename_once(tmp, path)
        except Exception:
            _cleanup_tmp(tmp)
            raise
    file_io.with_retry(_do)


def atomic_write_text(path: str, text: str) -> None:
    """tmp + rename text write through the file_io scheme registry — the
    shared primitive for model snapshots and the manifest."""
    _atomic_write(path, text, binary=False)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Binary sibling of ``atomic_write_text`` — also the sharded
    continuous fleet's commit-record/artifact write primitive."""
    _atomic_write(path, data, binary=True)


_atomic_write_bytes = atomic_write_bytes     # internal callers


def restore_barrier(iteration: int, timeout_s: float = 600.0) -> None:
    """Mesh barrier after a distributed restore: all ranks rendezvous and
    must have restored the SAME iteration.

    Prefers the jax.distributed coordination-service barrier (works on
    every backend — device collectives are unavailable on multi-process
    CPU meshes) with the restored iteration baked into the barrier id, so
    ranks that loaded different checkpoints time out instead of training
    on diverged state.  Falls back to a host allgather for externally
    injected collectives (LGBM_NetworkInitWithFunctions)."""
    from ..parallel.mesh import (comm_size, external_collectives,
                                 host_allgather)
    if comm_size() <= 1:
        return
    if external_collectives() is None:
        try:
            from jax._src import distributed as _jd
            client = getattr(_jd.global_state, "client", None)
        except ImportError:
            client = None
        if client is not None:
            try:
                client.wait_at_barrier(
                    f"lgbm_tpu_checkpoint_restore_{iteration}",
                    timeout_in_ms=int(timeout_s * 1000))
                return
            except Exception as e:
                raise LightGBMError(
                    "distributed restore barrier failed — a rank restored "
                    f"a different iteration than {iteration}, or died "
                    "before the rendezvous. checkpoint_dir must be shared "
                    f"storage visible to every worker ({e})") from e
    its = host_allgather(np.asarray([iteration], np.int64)).reshape(-1)
    if not (its == its[0]).all():
        raise LightGBMError(
            f"distributed restore diverged: ranks restored iterations "
            f"{its.tolist()} — checkpoint_dir must be shared storage "
            "visible to every worker")


class CheckpointManager:
    """Save/discover/load TrainState checkpoints under one directory."""

    def __init__(self, directory: str, keep: int = 3,
                 prefix: str = "ckpt"):
        if not directory:
            raise ValueError("CheckpointManager requires a directory")
        self.directory = directory.rstrip("/")
        self.keep = max(int(keep), 1)
        self.prefix = prefix
        self.total_save_s = 0.0           # accumulated write overhead
        self.saves = 0
        file_io.makedirs(self.directory)

    # -- paths ---------------------------------------------------------
    def _path(self, iteration: int) -> str:
        return (f"{self.directory}/{self.prefix}_{iteration:08d}"
                f"{CHECKPOINT_SUFFIX}")

    @property
    def manifest_path(self) -> str:
        return f"{self.directory}/MANIFEST.json"

    # -- write side ----------------------------------------------------
    def is_writer(self) -> bool:
        """Rank-0-only writes (module docstring; reference SURVEY §5)."""
        from ..parallel.mesh import comm_rank
        return comm_rank() == 0

    def save(self, state: TrainState,
             iteration: Optional[int] = None) -> Optional[str]:
        """Atomically persist ``state``; returns the committed path, or
        None on non-writer ranks."""
        if not self.is_writer():
            return None
        it = int(state.iteration if iteration is None else iteration)
        t0 = time.perf_counter()
        with timed("checkpoint::save"):
            path = self._path(it)
            _atomic_write_bytes(path, state.to_bytes())
            self._write_manifest()
            self._retain()
        self.total_save_s += time.perf_counter() - t0
        self.saves += 1
        return path

    def _write_manifest(self) -> None:
        import json
        entries = [{"iteration": it, "file": p.rsplit("/", 1)[-1]}
                   for it, p in self.checkpoints(scan_only=True)]
        atomic_write_text(self.manifest_path, json.dumps(
            {"format": "lightgbm_tpu-checkpoint-manifest",
             "keep": self.keep, "checkpoints": entries}))

    def _retain(self) -> None:
        """Keep the newest ``keep`` checkpoints; best-effort deletes (a
        reader may hold an old file open on some backends)."""
        ckpts = self.checkpoints(scan_only=True)
        for it, path in ckpts[:-self.keep]:
            try:
                file_io.remove(path)
            except OSError as e:
                log_warning(f"could not prune old checkpoint {path}: {e}")

    def clear(self) -> None:
        """Remove every checkpoint + the manifest (rank-0-only).
        resume=never semantics: a run that explicitly ignores existing
        checkpoints must not leave stale higher-iteration files behind
        for a later resume=auto to pick up."""
        if not self.is_writer():
            return
        for _, path in self.checkpoints(scan_only=True):
            try:
                file_io.remove(path)
            except OSError as e:
                log_warning(f"could not remove checkpoint {path}: {e}")
        if file_io.exists(self.manifest_path):
            try:
                file_io.remove(self.manifest_path)
            except OSError:
                pass

    # -- read side -----------------------------------------------------
    def checkpoints(self, scan_only: bool = False) -> List[Tuple[int, str]]:
        """(iteration, path) pairs sorted ascending.  Directory scan is
        authoritative (a crash can leave the manifest one step behind);
        the manifest exists for operators and remote schemes whose list
        op is expensive."""
        out = {}
        try:
            names = file_io.listdir(self.directory)
        except OSError:
            names = []
        for name in names:
            m = _NAME_RE.match(name)
            if m and m.group("prefix") == self.prefix:
                out[int(m.group("iter"))] = f"{self.directory}/{name}"
        if not out and not scan_only and file_io.exists(self.manifest_path):
            import json
            with file_io.open_readable(self.manifest_path) as fh:
                data = json.load(fh)
            for ent in data.get("checkpoints", []):
                out[int(ent["iteration"])] = \
                    f"{self.directory}/{ent['file']}"
        return sorted(out.items())

    def latest(self, verify: bool = False) -> Optional[str]:
        """Newest checkpoint path, or None.

        ``verify=True`` additionally proves the file LOADS (full read +
        member sha256 + parse), walking back to the newest VERIFIABLE
        checkpoint when the newest file is corrupt or truncated — the
        manifest and directory listing only prove a name exists, and a
        reader that trusts them resumes into a crash loop when the last
        write was torn."""
        if not verify:
            ckpts = self.checkpoints()
            return ckpts[-1][1] if ckpts else None
        for _, path in self._verified_newest_first():
            return path
        return None

    def _verified_newest_first(self):
        """Yield ``(TrainState, path)`` newest-first, skipping (and
        warning about) every checkpoint that fails to read or verify —
        the single corrupt-fallback walk behind latest(verify=True) and
        load_latest."""
        for _, path in reversed(self.checkpoints()):
            try:
                yield self._load_verified(path), path
            except (CheckpointCorruptError, OSError) as exc:
                log_warning(
                    f"skipping unusable checkpoint {path}: {exc} — "
                    "falling back to the previous retained checkpoint")

    def _load_verified(self, path: str) -> TrainState:
        data = file_io.read_bytes(path)     # whole-read retried
        return TrainState.from_bytes(data)  # checksum-verified

    def load(self, path: Optional[str] = None) -> TrainState:
        """Load one checkpoint (the latest by default).  An EXPLICIT path
        hard-fails on corruption — the caller asked for that file;
        use load_latest() for the skip-corrupt fallback behavior."""
        path = path or self.latest()
        if path is None:
            raise LightGBMError(
                f"no checkpoint found under {self.directory}")
        state = self._load_verified(path)
        log_info(f"loaded checkpoint {path} (iteration {state.iteration})")
        return state

    def load_latest(self) -> Optional[TrainState]:
        """Newest VERIFIABLE state or None when the directory holds no
        usable checkpoint (the auto-resume probe).  Corrupt or truncated
        files — a torn write that somehow got committed, bit rot, a
        half-synced remote store — are skipped with a warning instead of
        failing the resume: an older good checkpoint re-trains a few
        iterations; a crash loop re-trains nothing."""
        for state, path in self._verified_newest_first():
            log_info(f"loaded checkpoint {path} "
                     f"(iteration {state.iteration})")
            return state
        return None
