"""Env-driven fault injection: kill a chosen rank at a chosen iteration.

The synchronous-SPMD failure model (cluster.py / SURVEY §5) is only
testable if worker death is reproducible on demand.  These hooks let a
test (or a chaos-engineering harness) schedule one fault:

    LGBM_TPU_FAULT_ITER=<k>     fire when training reaches iteration k
                                (0-based, BEFORE the iteration runs)
    LGBM_TPU_FAULT_CYCLE=<c>    fire when a continuous service reaches
                                CYCLE c (0-based, after the cycle's
                                segments were polled but BEFORE its model
                                is committed — the two-phase ingest
                                window the sharded service's replay must
                                cover)
    LGBM_TPU_FAULT_REQUEST=<n>  fire when a serving replica has ADMITTED
                                its n-th predict request (1-based, BEFORE
                                serving it — the in-flight request is
                                lost with the process, which is exactly
                                the case the fleet router's retry must
                                absorb)
    LGBM_TPU_FAULT_RANK=<r>     only on this rank (default 0; training
                                faults only — replicas are single-process)
    LGBM_TPU_FAULT_MODE=exit    die like a preempted worker: os._exit,
                                no cleanup, no atexit (default)
    LGBM_TPU_FAULT_MODE=raise   raise InjectedWorkerFault instead — the
                                in-process variant for fast tier-1 tests
    LGBM_TPU_FAULT_EXIT_CODE    exit status for mode=exit (default 43)

GRAY faults (the rank stays ALIVE — passing health checks, renewing
nothing — which is exactly what the training fleet's bounded barriers,
rank leases and quorum cycle commit exist to survive):

    LGBM_TPU_FAULT_BARRIER=<n>  the fault rank's n-th FleetComm barrier
                                call (1-based, per process) stalls for
                                LGBM_TPU_FAULT_STALL_S seconds before
                                participating — peers see a barrier
                                deadline, not a death
    LGBM_TPU_FAULT_RANK_STALL=<c>
                                at continuous cycle c, AFTER the cycle's
                                segments were polled and journaled as
                                prepared (an idle poll at cycle c keeps
                                waiting for real work), the fault rank
                                sleeps LGBM_TPU_FAULT_STALL_S seconds
                                mid-phase: alive, answering nothing,
                                renewing no lease — the canonical gray
                                failure
    LGBM_TPU_FAULT_EXCHANGE_TORN=<n>
                                the fault rank's n-th filesystem exchange
                                write lands TORN (truncated payload under
                                a correct sha256 sidecar); the real bytes
                                follow after LGBM_TPU_FAULT_TORN_DELAY_S
                                seconds — readers must skip-and-retry,
                                never crash on the torn npz
    LGBM_TPU_FAULT_STALL_S      stall duration for BARRIER/RANK_STALL
                                (default 30)
    LGBM_TPU_FAULT_TORN_DELAY_S seconds before the good exchange bytes
                                replace the torn ones (default 0.5)

Every fired fault increments an in-process counter
(``fault_fired_count``) and writes a greppable ``LGBM_TPU_FAULT_FIRED
<name>`` line to stderr so multi-process soaks can assert each injected
fault actually fired.

The engine's training loop calls ``maybe_inject_fault(it)`` each
iteration and the serving front-end calls its own
``RequestFaultLatch.maybe_inject(count)`` per admitted predict; with no
fault env set each is a single dict lookup.  Both supervisors (cluster.py's
training supervisor and fleet/supervisor.py's replica supervisor) strip
LGBM_TPU_FAULT_* from child environments on restart attempts, modelling a
TRANSIENT fault (a preemption that does not recur) so the relaunched
job/replica can finish.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

__all__ = ["InjectedWorkerFault", "fault_spec", "maybe_inject_fault",
           "cycle_fault_spec", "maybe_inject_cycle_fault",
           "request_fault_spec", "RequestFaultLatch",
           "barrier_fault_spec", "maybe_inject_barrier_stall",
           "rank_stall_spec", "maybe_inject_rank_stall",
           "exchange_torn_spec", "fault_fired", "fault_fired_count",
           "FAULT_ENV_VARS", "DEFAULT_FAULT_EXIT_CODE"]

FAULT_ITER_ENV = "LGBM_TPU_FAULT_ITER"
FAULT_CYCLE_ENV = "LGBM_TPU_FAULT_CYCLE"
FAULT_REQUEST_ENV = "LGBM_TPU_FAULT_REQUEST"
FAULT_RANK_ENV = "LGBM_TPU_FAULT_RANK"
FAULT_MODE_ENV = "LGBM_TPU_FAULT_MODE"
FAULT_EXIT_CODE_ENV = "LGBM_TPU_FAULT_EXIT_CODE"
FAULT_BARRIER_ENV = "LGBM_TPU_FAULT_BARRIER"
FAULT_RANK_STALL_ENV = "LGBM_TPU_FAULT_RANK_STALL"
FAULT_EXCHANGE_TORN_ENV = "LGBM_TPU_FAULT_EXCHANGE_TORN"
FAULT_STALL_S_ENV = "LGBM_TPU_FAULT_STALL_S"
FAULT_TORN_DELAY_S_ENV = "LGBM_TPU_FAULT_TORN_DELAY_S"
FAULT_ENV_VARS = (FAULT_ITER_ENV, FAULT_CYCLE_ENV, FAULT_REQUEST_ENV,
                  FAULT_RANK_ENV, FAULT_MODE_ENV, FAULT_EXIT_CODE_ENV,
                  FAULT_BARRIER_ENV, FAULT_RANK_STALL_ENV,
                  FAULT_EXCHANGE_TORN_ENV, FAULT_STALL_S_ENV,
                  FAULT_TORN_DELAY_S_ENV)
DEFAULT_FAULT_EXIT_CODE = 43

# in-process fired counters (name -> count): soaks and unit tests assert
# every injected fault actually FIRED, the same contract as chaosio and
# chaosnet counters.  Multi-process harnesses grep the stderr line.
_FIRED: dict = {}


def fault_fired(name: str, detail: str = "") -> None:
    _FIRED[name] = _FIRED.get(name, 0) + 1
    sys.stderr.write(f"LGBM_TPU_FAULT_FIRED {name} {detail}\n")
    sys.stderr.flush()


def fault_fired_count(name: str) -> int:
    return _FIRED.get(name, 0)


def _stall_seconds() -> float:
    return float(os.environ.get(FAULT_STALL_S_ENV, "30") or 30)


class InjectedWorkerFault(RuntimeError):
    """Raised in place of process death when LGBM_TPU_FAULT_MODE=raise."""


def fault_spec() -> Optional[dict]:
    """Parse the fault env vars; None when no fault is scheduled."""
    raw = os.environ.get(FAULT_ITER_ENV)
    if raw is None or raw == "":
        return None
    return {
        "iteration": int(raw),
        "rank": int(os.environ.get(FAULT_RANK_ENV, "0") or 0),
        "mode": os.environ.get(FAULT_MODE_ENV, "exit") or "exit",
        "exit_code": int(os.environ.get(FAULT_EXIT_CODE_ENV,
                                        str(DEFAULT_FAULT_EXIT_CODE))),
    }


def maybe_inject_fault(iteration: int) -> None:
    """Die (or raise) if a fault is scheduled for this rank+iteration."""
    spec = fault_spec()
    if spec is None or iteration != spec["iteration"]:
        return
    from ..parallel.mesh import comm_rank
    if comm_rank() != spec["rank"]:
        return
    if spec["mode"] == "raise":
        raise InjectedWorkerFault(
            f"injected fault at iteration {iteration} "
            f"(rank {spec['rank']})")
    sys.stderr.write(f"LGBM_TPU_FAULT: killing rank {spec['rank']} at "
                     f"iteration {iteration}\n")
    sys.stdout.flush()
    sys.stderr.flush()
    # a preempted TPU worker gets no goodbye: skip atexit, GC, flushes
    os._exit(spec["exit_code"])


def cycle_fault_spec() -> Optional[dict]:
    """Parse the continuous-cycle fault env; None when none scheduled."""
    raw = os.environ.get(FAULT_CYCLE_ENV)
    if raw is None or raw == "":
        return None
    return {
        "cycle": int(raw),
        "rank": int(os.environ.get(FAULT_RANK_ENV, "0") or 0),
        "mode": os.environ.get(FAULT_MODE_ENV, "exit") or "exit",
        "exit_code": int(os.environ.get(FAULT_EXIT_CODE_ENV,
                                        str(DEFAULT_FAULT_EXIT_CODE))),
    }


def maybe_inject_cycle_fault(cycle: int, rank: Optional[int] = None) -> None:
    """Die (or raise) if a fault is scheduled for this rank+cycle.

    The sharded continuous service calls this after POLLING a cycle's
    segments but before the cycle's two-phase commit, so the injected
    death always lands in the window where segments were consumed from
    the source but their ingest position is not yet journaled — exactly
    the window the relaunch replay must make exactly-once.  ``rank``
    defaults to the mesh rank; the sharded service passes its fleet rank
    explicitly (in-process test fleets carry ranks the mesh knows
    nothing about)."""
    spec = cycle_fault_spec()
    if spec is None or cycle != spec["cycle"]:
        return
    if rank is None:
        from ..parallel.mesh import comm_rank
        rank = comm_rank()
    if rank != spec["rank"]:
        return
    if spec["mode"] == "raise":
        raise InjectedWorkerFault(
            f"injected fault at continuous cycle {cycle} "
            f"(rank {spec['rank']})")
    sys.stderr.write(f"LGBM_TPU_FAULT: killing rank {spec['rank']} at "
                     f"continuous cycle {cycle}\n")
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(spec["exit_code"])


def request_fault_spec() -> Optional[dict]:
    """Parse the serving-side fault env; None when none is scheduled."""
    raw = os.environ.get(FAULT_REQUEST_ENV)
    if raw is None or raw == "":
        return None
    return {
        "request": int(raw),
        "mode": os.environ.get(FAULT_MODE_ENV, "exit") or "exit",
        "exit_code": int(os.environ.get(FAULT_EXIT_CODE_ENV,
                                        str(DEFAULT_FAULT_EXIT_CODE))),
    }


# mode=raise survives the "death": latch per scheduled count so ONE fault
# fires per schedule (the contract), not one per subsequent request —
# otherwise an in-process replica would fail every predict forever while
# still answering health polls, flapping instead of dying once.  The
# latch lives PER CONSUMER (each ServingApp owns one, like its admitted-
# request counter): a module-global latch re-armed at every app
# construction would make an already-fired sibling fire again, since the
# ``>=`` schedule keeps matching every later count.
class RequestFaultLatch:
    """One-shot state for mode=raise; each ServingApp is an independent
    consumer of the schedule with its own request counter and latch."""

    def __init__(self) -> None:
        self._fired: Optional[int] = None

    def maybe_inject(self, count: int) -> None:
        """Die (or raise) if a fault is scheduled for this predict-request
        count.  ``>=`` rather than ``==``: concurrent admissions may skip
        past the exact count between the increment and this check, and a
        scheduled kill must not be lost to that race."""
        spec = request_fault_spec()
        if spec is None or count < spec["request"]:
            return
        if spec["mode"] == "raise":
            if self._fired == spec["request"]:
                return
            self._fired = spec["request"]
            raise InjectedWorkerFault(
                f"injected fault at serving request {count}")
        sys.stderr.write(f"LGBM_TPU_FAULT: killing replica at request "
                         f"{count}\n")
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(spec["exit_code"])


# ---------------------------------------------------------------------------
# Gray faults: the rank stays alive.  These never kill the process — the
# whole point is a worker that passes liveness checks while making no
# progress, which kill-based injection cannot model.
# ---------------------------------------------------------------------------
def barrier_fault_spec() -> Optional[dict]:
    """Parse the FleetComm barrier-stall fault; None when none set."""
    raw = os.environ.get(FAULT_BARRIER_ENV)
    if raw is None or raw == "":
        return None
    return {"barrier": int(raw),
            "rank": int(os.environ.get(FAULT_RANK_ENV, "0") or 0),
            "stall_s": _stall_seconds()}


def maybe_inject_barrier_stall(count: int, rank: int,
                               sleep_fn=None) -> None:
    """Stall (sleep, alive) before participating in this rank's
    ``count``-th FleetComm barrier.  The peers observe exactly what a
    gray rank produces: a barrier that never completes inside its
    deadline, from a process that is demonstrably still running."""
    spec = barrier_fault_spec()
    if spec is None or count != spec["barrier"] or rank != spec["rank"]:
        return
    fault_fired("barrier_stall",
                f"rank={rank} barrier={count} stall_s={spec['stall_s']}")
    import time
    (sleep_fn or time.sleep)(spec["stall_s"])


def rank_stall_spec() -> Optional[dict]:
    """Parse the mid-cycle rank-stall fault; None when none set."""
    raw = os.environ.get(FAULT_RANK_STALL_ENV)
    if raw is None or raw == "":
        return None
    return {"cycle": int(raw),
            "rank": int(os.environ.get(FAULT_RANK_ENV, "0") or 0),
            "stall_s": _stall_seconds()}


def maybe_inject_rank_stall(cycle: int, rank: int,
                            sleep_fn=None) -> None:
    """Sleep mid-cycle on the fault rank: segments polled and journaled
    as prepared, then nothing — no collectives, no lease renewals, no
    death.  The window where the fleet must choose between waiting
    forever (pre-hardening) and a quorum degraded commit."""
    spec = rank_stall_spec()
    if spec is None or cycle != spec["cycle"] or rank != spec["rank"]:
        return
    fault_fired("rank_stall",
                f"rank={rank} cycle={cycle} stall_s={spec['stall_s']}")
    import time
    (sleep_fn or time.sleep)(spec["stall_s"])


def exchange_torn_spec() -> Optional[dict]:
    """Parse the torn-exchange-write fault; None when none set."""
    raw = os.environ.get(FAULT_EXCHANGE_TORN_ENV)
    if raw is None or raw == "":
        return None
    return {"exchange": int(raw),
            "rank": int(os.environ.get(FAULT_RANK_ENV, "0") or 0),
            "delay_s": float(os.environ.get(FAULT_TORN_DELAY_S_ENV,
                                            "0.5") or 0.5)}
