"""Env-driven fault injection: kill a chosen rank at a chosen iteration.

The synchronous-SPMD failure model (cluster.py / SURVEY §5) is only
testable if worker death is reproducible on demand.  These hooks let a
test (or a chaos-engineering harness) schedule one fault:

    LGBM_TPU_FAULT_ITER=<k>     fire when training reaches iteration k
                                (0-based, BEFORE the iteration runs)
    LGBM_TPU_FAULT_RANK=<r>     only on this rank (default 0)
    LGBM_TPU_FAULT_MODE=exit    die like a preempted worker: os._exit,
                                no cleanup, no atexit (default)
    LGBM_TPU_FAULT_MODE=raise   raise InjectedWorkerFault instead — the
                                in-process variant for fast tier-1 tests
    LGBM_TPU_FAULT_EXIT_CODE    exit status for mode=exit (default 43)

The engine's training loop calls ``maybe_inject_fault(it)`` each
iteration; with no LGBM_TPU_FAULT_ITER set this is a single dict lookup.
The cluster supervisor (cluster.train_distributed) strips LGBM_TPU_FAULT_*
from worker environments on restart attempts, modelling a TRANSIENT fault
(a preemption that does not recur) so the relaunched job can finish.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

__all__ = ["InjectedWorkerFault", "fault_spec", "maybe_inject_fault",
           "FAULT_ENV_VARS", "DEFAULT_FAULT_EXIT_CODE"]

FAULT_ITER_ENV = "LGBM_TPU_FAULT_ITER"
FAULT_RANK_ENV = "LGBM_TPU_FAULT_RANK"
FAULT_MODE_ENV = "LGBM_TPU_FAULT_MODE"
FAULT_EXIT_CODE_ENV = "LGBM_TPU_FAULT_EXIT_CODE"
FAULT_ENV_VARS = (FAULT_ITER_ENV, FAULT_RANK_ENV, FAULT_MODE_ENV,
                  FAULT_EXIT_CODE_ENV)
DEFAULT_FAULT_EXIT_CODE = 43


class InjectedWorkerFault(RuntimeError):
    """Raised in place of process death when LGBM_TPU_FAULT_MODE=raise."""


def fault_spec() -> Optional[dict]:
    """Parse the fault env vars; None when no fault is scheduled."""
    raw = os.environ.get(FAULT_ITER_ENV)
    if raw is None or raw == "":
        return None
    return {
        "iteration": int(raw),
        "rank": int(os.environ.get(FAULT_RANK_ENV, "0") or 0),
        "mode": os.environ.get(FAULT_MODE_ENV, "exit") or "exit",
        "exit_code": int(os.environ.get(FAULT_EXIT_CODE_ENV,
                                        str(DEFAULT_FAULT_EXIT_CODE))),
    }


def maybe_inject_fault(iteration: int) -> None:
    """Die (or raise) if a fault is scheduled for this rank+iteration."""
    spec = fault_spec()
    if spec is None or iteration != spec["iteration"]:
        return
    from ..parallel.mesh import comm_rank
    if comm_rank() != spec["rank"]:
        return
    if spec["mode"] == "raise":
        raise InjectedWorkerFault(
            f"injected fault at iteration {iteration} "
            f"(rank {spec['rank']})")
    sys.stderr.write(f"LGBM_TPU_FAULT: killing rank {spec['rank']} at "
                     f"iteration {iteration}\n")
    sys.stdout.flush()
    sys.stderr.flush()
    # a preempted TPU worker gets no goodbye: skip atexit, GC, flushes
    os._exit(spec["exit_code"])
