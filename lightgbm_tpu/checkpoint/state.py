"""TrainState: the full resumable state of a training run.

The reference's only persistence is the model text (gbdt_model_text.cpp),
which is enough to PREDICT from but not to RESUME: the model text rounds
floats through ``%g`` fields, drops the in-bin thresholds the device
traversal needs, and carries none of the loop state (iteration counter,
DART drop bookkeeping, early-stopping bests, eval history).  TrainState
captures everything needed for a resumed run to be BIT-IDENTICAL to an
uninterrupted one:

- the tree list (pickled exactly — float64 leaf values, in-bin
  thresholds, linear-leaf coefficients survive byte-for-byte),
- the running train score (the f32 accumulation order matters, so the
  array is saved rather than recomputed),
- the iteration counter and per-mode extras (DART tree weights, stump
  flag, CEGB used-feature set) via GBDT.training_state_extra(),
- the per-iteration evaluation history, replayed through the callbacks
  on resume so early-stopping/record_evaluation closures reconstruct
  their exact state,
- a dataset fingerprint (bin-mapper hash + shape) verified on restore —
  resuming against different data silently corrupts the model, so it is
  a hard error instead.

RNG positions are deliberately NOT serialized: every sampler is
iteration-derived (bagging ``bagging_seed + iteration``, GOSS
``bagging_seed*65537 + iteration``, DART ``drop_seed + iteration``), so
position == iteration and restoring the counter restores the stream.

Serialization is a single zip archive (state.json + arrays.npz +
trees.pkl + a debug-only model.txt) so the manager can commit it with one
atomic rename.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import io
import json
import pickle
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from ..log import LightGBMError
from ..tree import Tree

__all__ = ["TrainState", "CheckpointCorruptError", "dataset_fingerprint",
           "verify_fingerprint", "capture_train_state",
           "restore_train_state", "FORMAT_VERSION", "CHECKSUMS_MEMBER"]

FORMAT_VERSION = 1
CHECKSUMS_MEMBER = "checksums.json"


class CheckpointCorruptError(LightGBMError):
    """The checkpoint bytes are damaged (truncated archive, failed member
    checksum, unreadable payload) — as opposed to a VALID checkpoint that
    doesn't match this run (fingerprint/meta mismatches stay plain
    LightGBMErrors).  The distinction matters to readers: corruption is
    recoverable by falling back to an older checkpoint; a mismatch means
    the caller is resuming the wrong run and must stop."""


# ----------------------------------------------------------------------
def dataset_fingerprint(handle) -> Dict[str, Any]:
    """Identity of a constructed TrainDataset: a hash over every bin
    mapper's boundaries plus the dataset shape.  Two datasets that agree
    here bin any row identically, which is exactly the property resumed
    training needs (trees reference bins, not raw values).

    For rank-sharded datasets the mapper hash is global (mappers are
    synced across ranks at load) while row counts are per-rank, so
    ``num_data`` carries the GLOBAL count there and the local count is
    skipped from the hash.
    """
    h = hashlib.sha256()
    for m in handle.all_bin_mappers:
        h.update(str(m.bin_type).encode())
        h.update(str(m.missing_type).encode())
        h.update(np.int64(m.num_bin).tobytes())
        if getattr(m, "bin_2_categorical", None):
            h.update(np.asarray(m.bin_2_categorical, np.int64).tobytes())
        elif getattr(m, "bin_upper_bound", None) is not None:
            h.update(np.asarray(m.bin_upper_bound, np.float64).tobytes())
    # targets matter as much as features: resuming with different labels
    # or weights would boost the restored trees against the wrong
    # objective while binning identically (metadata label/weight are
    # GLOBAL even on rank-sharded datasets, dataset.py allgather)
    md = handle.metadata
    t = hashlib.sha256()
    t.update(np.asarray(md.label, np.float32).tobytes())
    if md.weight is not None:
        t.update(np.asarray(md.weight, np.float32).tobytes())
    if md.init_score is not None:
        t.update(np.asarray(md.init_score, np.float64).tobytes())
    if md.query_boundaries is not None:
        t.update(np.asarray(md.query_boundaries, np.int64).tobytes())
    return {
        "mappers_sha256": h.hexdigest(),
        "targets_sha256": t.hexdigest(),
        "num_total_features": int(handle.num_total_features),
        "num_data": int(handle.num_data),
        "rank_local": bool(getattr(handle, "rank_local", False)),
    }


def verify_fingerprint(saved: Dict[str, Any], handle) -> None:
    """Refuse restore onto a dataset that does not match the checkpoint."""
    current = dataset_fingerprint(handle)
    mismatches = [k for k in ("mappers_sha256", "targets_sha256",
                              "num_total_features", "num_data")
                  if saved.get(k) != current.get(k)]
    if mismatches:
        raise LightGBMError(
            "checkpoint dataset fingerprint mismatch: the checkpoint was "
            f"written for a different dataset (differs in: "
            f"{', '.join(mismatches)}; saved={ {k: saved.get(k) for k in mismatches} } "
            f"current={ {k: current.get(k) for k in mismatches} }). "
            "Resuming would bin rows differently and corrupt the model — "
            "point checkpoint_dir at a fresh directory to start over, or "
            "train on the original data.")


def _json_scalar(obj):
    """json.dumps fallback for numpy scalars that slip into best_score or
    eval history through custom fevals."""
    if isinstance(obj, np.bool_):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    raise TypeError(f"not JSON serializable in checkpoint header: "
                    f"{type(obj).__name__}")


# ----------------------------------------------------------------------
@dataclasses.dataclass
class TrainState:
    """Everything needed to resume training bit-identically."""

    iteration: int
    trees: List[Tree]
    train_score: np.ndarray                 # [K, N] float32
    extra: Dict[str, Any]                   # GBDT.training_state_extra()
    eval_history: List[List[tuple]]         # per-iteration eval tuples
    best_iteration: int
    best_score: Dict[str, Dict[str, float]]
    fingerprint: Dict[str, Any]
    meta: Dict[str, Any]                    # boosting/objective/num_class

    # -- serialization -------------------------------------------------
    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        header = {
            "format_version": FORMAT_VERSION,
            "iteration": int(self.iteration),
            "best_iteration": int(self.best_iteration),
            "best_score": self.best_score,
            "eval_history": [[list(t) for t in ev]
                             for ev in self.eval_history],
            "fingerprint": self.fingerprint,
            "meta": self.meta,
        }
        arrays = io.BytesIO()
        np.savez(arrays, train_score=np.asarray(self.train_score,
                                                np.float32))
        members = {
            "state.json": json.dumps(header,
                                     default=_json_scalar).encode(),
            "arrays.npz": arrays.getvalue(),
            "trees.pkl": pickle.dumps(
                {"trees": _clean_trees(self.trees), "extra": self.extra},
                protocol=pickle.HIGHEST_PROTOCOL),
            "model.txt": self._debug_model_text().encode(),
        }
        # per-member sha256, written LAST: verify-on-load catches silent
        # byte corruption (bit rot, torn remote reads) that unzips fine —
        # a truncated archive already fails at the zip layer, a flipped
        # payload bit does not
        sums = {"algo": "sha256",
                "members": {name: hashlib.sha256(blob).hexdigest()
                            for name, blob in members.items()}}
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for name, blob in members.items():
                zf.writestr(name, blob)
            zf.writestr(CHECKSUMS_MEMBER, json.dumps(sums, sort_keys=True))
        return buf.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> "TrainState":
        try:
            with zipfile.ZipFile(io.BytesIO(data)) as zf:
                names = set(zf.namelist())
                if CHECKSUMS_MEMBER in names:
                    # verify BEFORE parsing: pickle/json must never see
                    # corrupt bytes (a flipped bit in a pickle stream can
                    # do anything from ValueError to a silently wrong
                    # object)
                    sums = json.loads(zf.read(CHECKSUMS_MEMBER))
                    for member, want in sums.get("members", {}).items():
                        if member not in names:
                            raise CheckpointCorruptError(
                                f"checkpoint member {member!r} listed in "
                                "checksums but missing from the archive")
                        got = hashlib.sha256(zf.read(member)).hexdigest()
                        if got != want:
                            raise CheckpointCorruptError(
                                f"checkpoint member {member!r} failed its "
                                f"sha256 check (stored {want[:12]}…, read "
                                f"{got[:12]}…): the file is corrupt")
                header = json.loads(zf.read("state.json"))
                if header.get("format_version") != FORMAT_VERSION:
                    raise LightGBMError(
                        "unsupported checkpoint format_version "
                        f"{header.get('format_version')!r} (this build "
                        f"reads {FORMAT_VERSION})")
                with np.load(io.BytesIO(zf.read("arrays.npz"))) as npz:
                    train_score = np.asarray(npz["train_score"])
                payload = pickle.loads(zf.read("trees.pkl"))
        except LightGBMError:
            raise              # corrupt (already typed) or version gate
        except Exception as exc:
            # BadZipFile/zlib errors (truncation), KeyError (missing
            # member), json/pickle decode failures: all one thing to a
            # reader — these bytes are not a usable checkpoint
            raise CheckpointCorruptError(
                f"corrupt checkpoint archive: {type(exc).__name__}: "
                f"{exc}") from exc
        return TrainState(
            iteration=int(header["iteration"]),
            trees=payload["trees"],
            train_score=train_score,
            extra=payload["extra"],
            eval_history=[[tuple(t) for t in ev]
                          for ev in header["eval_history"]],
            best_iteration=int(header["best_iteration"]),
            best_score=header["best_score"],
            fingerprint=header["fingerprint"],
            meta=header["meta"])

    def _debug_model_text(self) -> str:
        """Human-inspectable tree dump inside the archive.  NOT used for
        restore (the %g fields are lossy); trees.pkl is authoritative."""
        lines = [f"# lightgbm_tpu checkpoint (iteration={self.iteration}); "
                 "debug dump only — restore reads trees.pkl", ""]
        for i, t in enumerate(self.trees):
            lines.append(t.to_string(i))
        return "\n".join(lines)


def _clean_trees(trees: List[Tree]) -> List[Tree]:
    """Shallow-copy trees without device-array caches (the categorical
    mask cache holds jax Arrays; rebuilt lazily after restore)."""
    out = []
    for t in trees:
        if getattr(t, "_cat_mask_cache", None) is not None:
            t = copy.copy(t)
            t._cat_mask_cache = None
        out.append(t)
    return out


# ----------------------------------------------------------------------
def capture_train_state(booster,
                        eval_history: Optional[List[List[tuple]]] = None
                        ) -> TrainState:
    """Snapshot a live Booster mid-training.  Reading ``models`` flushes
    any pending device states first, so the captured tree list and score
    are consistent with ``iter_``."""
    gbdt = booster._gbdt
    if gbdt is None:
        raise LightGBMError("capture_train_state requires a training "
                            "Booster (not a loaded predictor)")
    trees = list(gbdt.models)              # flushes the fused pipeline
    return TrainState(
        iteration=int(gbdt.iter_),
        trees=trees,
        train_score=np.asarray(gbdt.train_score, np.float32),
        extra=gbdt.training_state_extra(),
        eval_history=[list(ev) for ev in (eval_history or [])],
        best_iteration=int(booster.best_iteration),
        best_score=dict(booster.best_score),
        fingerprint=dataset_fingerprint(gbdt.train_data),
        meta={
            "boosting": type(gbdt).__name__.lower(),
            "objective": gbdt.objective.name,
            "num_class": int(gbdt.num_class),
            "num_trees": len(trees),
        })


def restore_train_state(booster, state: TrainState) -> None:
    """Load a TrainState into a freshly constructed Booster (zero
    iterations trained, no valid sets added yet — valid-set score
    catch-up happens in add_valid, which replays the restored trees).

    Verifies the dataset fingerprint and the model-shape meta before
    touching anything, so a mismatch leaves the Booster untrained."""
    import jax.numpy as jnp

    gbdt = booster._gbdt
    if gbdt is None:
        raise LightGBMError("restore_train_state requires a training "
                            "Booster (not a loaded predictor)")
    if gbdt.iter_ != 0 or gbdt.models:
        raise LightGBMError("restore_train_state requires a fresh Booster "
                            f"(this one already trained {gbdt.iter_} "
                            "iterations)")
    verify_fingerprint(state.fingerprint, gbdt.train_data)
    expect = type(gbdt).__name__.lower()
    if state.meta.get("boosting") != expect:
        raise LightGBMError(
            f"checkpoint was written by boosting={state.meta.get('boosting')!r}"
            f" but this run uses boosting={expect!r}")
    if int(state.meta.get("num_class", 1)) != gbdt.num_class:
        raise LightGBMError(
            f"checkpoint num_class={state.meta.get('num_class')} != "
            f"configured num_class={gbdt.num_class}")
    if len(state.trees) != state.iteration * gbdt.num_class:
        raise LightGBMError(
            f"corrupt checkpoint: {len(state.trees)} trees for "
            f"{state.iteration} iterations x {gbdt.num_class} classes")
    score = np.asarray(state.train_score, np.float32)
    # the saved score spans the DEVICE rows: with train_row_buckets on
    # that includes the bucket padding (same config ⇒ same bucket, the
    # fingerprint already pinned the real row count)
    n_dev = int(getattr(gbdt.train_data, "num_rows_device",
                        gbdt.train_data.num_data))
    if score.shape != (gbdt.num_class, n_dev):
        raise LightGBMError(
            f"corrupt checkpoint: train_score shape {score.shape} != "
            f"{(gbdt.num_class, n_dev)}")

    gbdt.models = list(state.trees)
    gbdt.iter_ = int(state.iteration)
    gbdt.train_score = jnp.asarray(score)
    # stateful objective RNG streams (rank_xendcg's per-round gamma key)
    # advance past the restored rounds, so the resumed run draws the
    # same sequence an uninterrupted one would
    gbdt.objective.fused_advance(int(state.iteration))
    gbdt.load_training_state_extra(dict(state.extra))
    booster.best_iteration = int(state.best_iteration)
    booster.best_score = dict(state.best_score)
    booster._invalidate_stacked()
