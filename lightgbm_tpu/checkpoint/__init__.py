"""Fault-tolerant training: checkpoint/restore subsystem.

cluster.py declares the failure model — synchronous SPMD, a dead worker
fails the job, recovery is checkpoint-restart (SURVEY §5) — and this
package implements the restart half:

- ``TrainState`` / ``capture_train_state`` / ``restore_train_state``
  (state.py): the full resumable state — trees (exact), running score,
  iteration, per-mode extras, eval history, early-stopping bests — plus
  a dataset fingerprint verified on restore.
- ``CheckpointManager`` (manager.py): atomic tmp+rename writes through
  the io/file_io scheme registry, MANIFEST.json, ``latest()`` discovery,
  keep-last-N retention, rank-0-only writes, ``restore_barrier`` for
  distributed restores.
- fault injection (fault.py): ``LGBM_TPU_FAULT_ITER`` kills a chosen
  rank at a chosen iteration so the whole recovery path is testable.

Wiring: ``engine.train(..., checkpoint_dir=...)`` (or the config params
``checkpoint_dir``/``checkpoint_freq``/``keep_checkpoints``/``resume``)
saves every ``checkpoint_freq`` iterations and auto-resumes from the
latest checkpoint; ``cluster.train_distributed`` supervises workers and
relaunches the job from the latest checkpoint on worker death.
"""

from .fault import (DEFAULT_FAULT_EXIT_CODE, FAULT_ENV_VARS,
                    InjectedWorkerFault, fault_spec, maybe_inject_fault)
from .manager import (CHECKPOINT_SUFFIX, CheckpointManager,
                      atomic_write_text, restore_barrier)
from .state import (FORMAT_VERSION, CheckpointCorruptError, TrainState,
                    capture_train_state, dataset_fingerprint,
                    restore_train_state, verify_fingerprint)

__all__ = [
    "TrainState", "capture_train_state", "restore_train_state",
    "dataset_fingerprint", "verify_fingerprint", "FORMAT_VERSION",
    "CheckpointCorruptError",
    "CheckpointManager", "restore_barrier", "atomic_write_text",
    "CHECKPOINT_SUFFIX",
    "InjectedWorkerFault", "fault_spec", "maybe_inject_fault",
    "FAULT_ENV_VARS", "DEFAULT_FAULT_EXIT_CODE",
]
