"""Labeled phase timers + optional device profiler traces.

TPU-native equivalent of the reference's compile-time-gated label timer
(Common::Timer / FunctionTimer, utils/common.h:953-1017; singleton
global_timer printed at exit, src/boosting/gbdt.cpp:20).  Differences by
design: enabled at runtime via ``LIGHTGBM_TPU_TIMETAG=1`` (the reference
needs a -DTIMETAG rebuild), and ``device_trace`` wraps ``jax.profiler`` so a
phase can capture an XLA/TPU trace for xprof (the reference has no device
tracing story at all).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict

__all__ = ["global_timer", "timed", "device_trace", "timers_enabled"]

_ENABLED = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")


def timers_enabled() -> bool:
    return _ENABLED


class PhaseTimer:
    """name -> accumulated seconds, printed at exit (reference
    Common::Timer::Print semantics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.acc: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self.acc[name] = self.acc.get(name, 0.0) + seconds
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> str:
        lines = ["LightGBM-TPU phase timers:"]
        for name in sorted(self.acc, key=lambda k: -self.acc[k]):
            lines.append(f"  {name}: {self.acc[name]:.3f}s "
                         f"({self.counts[name]} calls)")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self.acc.clear()
            self.counts.clear()


global_timer = PhaseTimer()


@contextmanager
def timed(name: str, sync=None):
    """Accumulate wall-clock under `name` when timers are enabled.

    sync: optional array/pytree to block_until_ready before stopping the
    clock, so async-dispatched device work is attributed to the phase that
    launched it instead of whoever syncs next."""
    if not _ENABLED:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if sync is not None:
            import jax
            jax.block_until_ready(sync)
        global_timer.add(name, time.perf_counter() - t0)


@contextmanager
def device_trace(log_dir: str):
    """Capture a jax.profiler trace around the block (works on TPU and the
    CPU test mesh; view with xprof/tensorboard)."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@atexit.register
def _print_at_exit():
    if _ENABLED and global_timer.acc:
        from .log import log_info
        log_info(global_timer.report())
