"""Labeled phase timers + optional device profiler traces — compat shims.

Historically this module owned the timing state (the TPU-native equivalent
of the reference's compile-time label timer, Common::Timer /
FunctionTimer, utils/common.h:953-1017).  The state now lives in the
unified telemetry subsystem: ``timed`` is a thin wrapper over
``telemetry.spans.span`` and ``global_timer`` IS the span engine's
aggregate, so existing call sites keep working unchanged while their
timings also feed span recording and the exporters.

Enablement is runtime state (``set_enabled``) rather than frozen at
import; ``LIGHTGBM_TPU_TIMETAG=1`` remains the env-var default (the
reference needs a -DTIMETAG rebuild), and ``telemetry=on`` in the config
flips it programmatically.  ``device_trace`` wraps ``jax.profiler`` so a
phase can capture an XLA/TPU trace for xprof (the reference has no device
tracing story at all).
"""

from __future__ import annotations

import atexit
from contextlib import contextmanager

from .telemetry import spans as _spans
from .telemetry.spans import PhaseTimer, global_timer

__all__ = ["global_timer", "timed", "device_trace", "timers_enabled",
           "set_enabled", "PhaseTimer"]


def timers_enabled() -> bool:
    return _spans.enabled()


def set_enabled(value: bool) -> None:
    """Flip the phase timers at runtime (tests / ``telemetry=on``); the
    env var only sets the import-time default."""
    _spans.set_enabled(value)


# ``timed(name, sync=None)``: same contract as before — accumulate
# wall-clock under `name` when timers are enabled, blocking on `sync`
# first so async device work is attributed to the phase that launched it.
timed = _spans.span


@contextmanager
def device_trace(log_dir: str):
    """Capture a jax.profiler trace around the block (works on TPU and the
    CPU test mesh; view with xprof/tensorboard)."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@atexit.register
def _print_at_exit():
    if _spans.enabled() and global_timer.acc:
        from .log import log_info
        log_info(global_timer.report())
