"""Distributed request tracing + tail-sampled flight recorder.

PR 4's spans are process-local; since the fleet tier a single predict
crosses router -> hedge peer -> replica HTTP -> MicroBatcher queue ->
device flush, with breakers, retry budgets, and deadline squeezes deciding
its fate.  This module ties those hops together:

- a ``TraceSpan`` tree per request: the root is minted at the first traced
  hop (``Tracer.start_request``), children record routing decisions
  (pick / reroute / hedge / hedge-win), per-attempt forwards, replica
  admission, queue wait, and the device flush.  The wire context (trace
  id + parent span id + hop count + sampling verdict) rides the request
  body under ``BODY_KEY`` alongside the existing ``deadline_ms``, so HTTP
  hops propagate it for free.
- **head sampling + tail-based keep**: every traced request records its
  spans in memory (a handful of small objects); whether the finished
  trace is *persisted* is decided at completion — head-sampled traces
  (``sample_rate``) always keep, and tail rules force-keep anything
  interesting regardless of the coin flip: SLO breach, hedged, rerouted,
  breaker involvement, 503/504 death.  A hedge duplicate carries a
  ``keep`` hint in its wire context so the downstream hop persists its
  half of a trace the root already marked.
- **flight recorder**: a bounded ring of the most recent completed traces
  per process (kept or not), dumped to disk on demand and — rate-limited
  — when the router sees a failure burst (breaker open, shed, partial
  publish).  ``GET /v1/trace/recent`` and ``GET /v1/trace/<id>`` serve it;
  the router's ``/v1/trace/<id>`` additionally fans out to its replicas
  and assembles the cross-process span set.
- **per-rank JSONL sink**: kept traces append one JSON line per span to
  ``trace_spans_rank<R>-<pid>.jsonl`` under ``trace_dir``;
  ``telemetry.export.assemble_traces`` groups any number of rank files by
  trace id and renders the merged set through the Chrome-trace writer.

The disabled fast path is one attribute read returning ``None``; every
call site guards on that, so ``trace_requests=false`` is a no-op on the
hot path (guard-tested).
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from .. import log as _log
from . import spans as _spans

__all__ = ["BODY_KEY", "TraceSpan", "Tracer", "FlightRecorder", "TRACER",
           "activate", "current", "current_trace_id", "child_span",
           "configure_from_config"]

# request-body key the wire context rides under (next to deadline_ms)
BODY_KEY = "trace"

# wall-clock epoch matching perf_counter 0 (same convention as spans.py)
_EPOCH = time.time() - time.perf_counter()

_ids = itertools.count(1)
_PID = os.getpid()


def _new_span_id() -> str:
    # unique across processes without uuid cost: pid tag + local counter
    return f"{_PID:x}.{next(_ids)}"


# trace ids only need to be unique and unguessable-enough to never
# collide across a fleet; a seeded-per-process SystemRandom-free 64-bit
# draw is ~4x cheaper than uuid4 on the mint path
_id_rng = random.Random(int.from_bytes(os.urandom(8), "big") ^ _PID)
_id_lock = threading.Lock()


def _new_trace_id() -> str:
    with _id_lock:
        return f"{_id_rng.getrandbits(64):016x}"


class TraceSpan:
    """One node of a request's span tree (always owned by a ``_Trace``)."""

    __slots__ = ("_trace", "span_id", "parent_id", "name", "start_unix_s",
                 "_t0", "dur_s", "thread_id", "attrs", "finished")

    def __init__(self, trace: "_Trace", name: str,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self._trace = trace
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.name = name
        self._t0 = time.perf_counter()
        self.start_unix_s = self._t0 + _EPOCH
        self.dur_s = 0.0
        self.thread_id = threading.get_ident()
        self.finished = False
        # ownership, not a copy: every caller passes a fresh kwargs dict
        self.attrs = attrs

    # ------------------------------------------------------------------
    @property
    def trace_id(self) -> str:
        return self._trace.trace_id

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def mark(self, reason: str) -> None:
        """Tail-based keep rule: a trace marked with any reason is
        persisted regardless of the head-sampling coin flip."""
        self._trace.mark(reason)

    def child(self, name: str, **attrs) -> "TraceSpan":
        return self._trace.add_span(name, self.span_id, attrs)

    def event(self, name: str, **attrs) -> "TraceSpan":
        """Zero-duration child: a point-in-time decision (pick, hedge,
        reroute, verdict) stamped on the timeline."""
        e = self.child(name, **attrs)
        e.dur_s = 0.0
        e.finished = True
        return e

    def child_at(self, name: str, start_perf_s: float, dur_s: float,
                 **attrs) -> "TraceSpan":
        """Child with explicit timing — for phases measured elsewhere
        (queue wait from t_enqueue, a shared device flush)."""
        c = self.child(name, **attrs)
        c._t0 = float(start_perf_s)
        c.start_unix_s = c._t0 + _EPOCH
        c.dur_s = float(dur_s)
        c.finished = True
        return c

    def finish(self) -> None:
        self.dur_s = time.perf_counter() - self._t0
        self.finished = True

    def finish_request(self, status: Optional[int] = None, **attrs) -> None:
        """Finish the ROOT span and complete its trace (tail rules, ring,
        sink)."""
        if attrs:
            self.attrs.update(attrs)
        self.finish()
        self._trace.complete(status)

    def discard(self) -> None:
        """Drop the trace without recording it anywhere (e.g. an idle
        continuous poll that turned out not to be a cycle)."""
        self._trace.discarded = True

    def wire(self) -> Dict[str, Any]:
        """Context to propagate to the next hop (request-body dict)."""
        t = self._trace
        return {"id": t.trace_id, "parent": self.span_id,
                "hop": t.hop + 1, "sampled": t.sampled,
                # downstream hops persist their half of a trace this
                # process already decided to keep (e.g. a hedge duplicate
                # — the mark happens before the duplicate is sent)
                "keep": bool(t.keep)}

    def to_dict(self) -> Dict[str, Any]:
        d = {"trace_id": self._trace.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "start_unix_s": self.start_unix_s, "dur_s": self.dur_s,
             "thread_id": self.thread_id, "rank": self._trace.rank,
             "pid": _PID, "attrs": dict(self.attrs)}
        if not self.finished:
            # serialized mid-flight (a hedge-abandoned primary attempt
            # when its root completes): dur_s is CENSORED, not zero —
            # say so instead of letting analysis read it as instant
            d["in_flight"] = True
        return d


class _Trace:
    """Process-local span set of one request/cycle."""

    __slots__ = ("tracer", "trace_id", "hop", "sampled", "rank", "spans",
                 "keep", "root", "discarded", "_lock", "_completed")

    def __init__(self, tracer: "Tracer", trace_id: str, hop: int,
                 sampled: bool):
        self.tracer = tracer
        self.trace_id = trace_id
        self.hop = hop
        self.sampled = sampled
        self.rank = tracer.rank
        self.spans: List[TraceSpan] = []
        self.keep: set = set()
        self.root: Optional[TraceSpan] = None
        self.discarded = False
        self._completed = False
        self._lock = threading.Lock()

    def add_span(self, name: str, parent_id: Optional[str],
                 attrs: Dict[str, Any]) -> TraceSpan:
        s = TraceSpan(self, name, parent_id, attrs)
        with self._lock:
            self.spans.append(s)
        return s

    def mark(self, reason: str) -> None:
        with self._lock:
            self.keep.add(str(reason))

    def complete(self, status: Optional[int]) -> None:
        with self._lock:
            if self._completed:
                return
            self._completed = True
        if not self.discarded:
            self.tracer._complete(self, status)

    def to_dict(self, status: Optional[int], kept: bool,
                include_spans: bool = True) -> Dict[str, Any]:
        root = self.root
        with self._lock:
            spans = ([s.to_dict() for s in self.spans]
                     if include_spans else None)
            keep = sorted(self.keep)
        out = {"trace_id": self.trace_id, "root": root.name,
               "model": root.attrs.get("model"),
               "status": status, "kept": kept, "keep": keep,
               "sampled": self.sampled, "hop": self.hop,
               "start_unix_s": root.start_unix_s,
               "dur_ms": round(root.dur_s * 1e3, 3),
               "rank": self.rank, "pid": _PID}
        if include_spans:
            out["spans"] = spans
        return out


class FlightRecorder:
    """Bounded ring of recently COMPLETED traces (kept or not): the
    per-process black box the trace routes and burst dumps read.

    The ring holds live ``_Trace`` objects and serializes LAZILY at read
    time: pushes happen once per request on the hot path, reads happen
    when a human (or a burst dump) asks — building the span dicts per
    request was the dominant measured tracing overhead."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(capacity), 1))

    def push(self, trace: "_Trace", status: Optional[int],
             kept: bool) -> None:
        with self._lock:
            self._ring.append((trace, status, kept))

    def resize(self, capacity: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(int(capacity), 1))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> List[Dict]:
        """Newest-first full trace dicts."""
        with self._lock:
            items = list(reversed(self._ring))
        return [t.to_dict(status, kept) for t, status, kept in items]

    def recent(self, limit: int = 100) -> List[Dict]:
        """Newest-first summaries (no spans) for ``/v1/trace/recent``."""
        with self._lock:
            items = list(reversed(self._ring))[:max(int(limit), 1)]
        return [t.to_dict(status, kept, include_spans=False)
                for t, status, kept in items]

    def get(self, trace_id: str) -> Optional[Dict]:
        with self._lock:
            items = list(reversed(self._ring))
        for t, status, kept in items:
            if t.trace_id == trace_id:
                return t.to_dict(status, kept)
        return None


class Tracer:
    """Per-process tracing policy + sinks.  ``TRACER`` is the module
    default every component falls back to; tests and benches construct
    their own."""

    # burst dumps are rate-limited so a flapping breaker cannot turn the
    # flight recorder into a disk-filling loop
    _DUMP_MIN_INTERVAL_S = 30.0

    def __init__(self, enabled: bool = False, sample_rate: float = 0.01,
                 ring: int = 256, trace_dir: str = "",
                 keep_slo_ms: float = 0.0, rank: int = 0,
                 sink_path: Optional[str] = None):
        self._enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self.trace_dir = str(trace_dir or "")
        self.keep_slo_ms = float(keep_slo_ms)
        self.rank = int(rank)
        self.recorder = FlightRecorder(ring)
        self._sink_path = sink_path
        self._sink = None
        self._sink_lock = threading.Lock()
        self._rng = random.Random()
        self._last_dump_s = 0.0
        self.dumps: List[str] = []

    # ------------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: Optional[bool] = None,
                  sample_rate: Optional[float] = None,
                  ring: Optional[int] = None,
                  trace_dir: Optional[str] = None,
                  keep_slo_ms: Optional[float] = None,
                  rank: Optional[int] = None,
                  sink_path: Optional[str] = None) -> "Tracer":
        if enabled is not None:
            self._enabled = bool(enabled)
        if sample_rate is not None:
            self.sample_rate = float(sample_rate)
        if ring is not None:
            self.recorder.resize(ring)
        if keep_slo_ms is not None:
            self.keep_slo_ms = float(keep_slo_ms)
        if rank is not None:
            self.rank = int(rank)
        if trace_dir is not None and str(trace_dir) != self.trace_dir:
            self.trace_dir = str(trace_dir)
            self._close_sink()
        if sink_path is not None and sink_path != self._sink_path:
            self._sink_path = sink_path
            self._close_sink()
        return self

    # ------------------------------------------------------------------
    def _sample(self) -> bool:
        r = self.sample_rate
        if r <= 0.0:
            return False
        if r >= 1.0:
            return True
        return self._rng.random() < r

    def start_request(self, name: str, ctx: Optional[Dict] = None,
                      **attrs) -> Optional[TraceSpan]:
        """Root span of this process's part of a request.  ``ctx`` is the
        upstream wire context (request body ``trace`` dict) — adopted
        when present, minted otherwise.  Returns None when disabled (the
        whole fast-path cost)."""
        if not self._enabled:
            return None
        parent = None
        if isinstance(ctx, dict) and ctx.get("id"):
            trace_id = str(ctx["id"])
            parent = ctx.get("parent")
            try:
                hop = int(ctx.get("hop", 1))
            except (TypeError, ValueError):
                hop = 1
            sampled = bool(ctx.get("sampled"))
            tr = _Trace(self, trace_id, hop, sampled)
            if ctx.get("keep"):
                # the upstream hop already decided this trace matters
                # (e.g. it is a hedge duplicate): persist our half too
                tr.keep.add("upstream")
        else:
            tr = _Trace(self, _new_trace_id(), 0, self._sample())
        root = tr.add_span(name, parent, attrs)
        tr.root = root
        return root

    def start_cycle(self, name: str, **attrs) -> Optional[TraceSpan]:
        """Root span of a continuous-training cycle: cycles are rare and
        each one matters, so they bypass sampling (always kept)."""
        if not self._enabled:
            return None
        tr = _Trace(self, _new_trace_id(), 0, True)
        tr.keep.add("cycle")
        root = tr.add_span(name, None, attrs)
        tr.root = root
        return root

    # -- completion ----------------------------------------------------
    def _complete(self, trace: _Trace, status: Optional[int]) -> None:
        root = trace.root
        dur_ms = root.dur_s * 1e3
        slo_ms = root.attrs.get("slo_ms") or self.keep_slo_ms
        if slo_ms and dur_ms > float(slo_ms):
            trace.mark("slo_breach")
        if status in (503, 504):
            trace.mark(f"status_{status}")
        elif status is not None and status >= 500:
            trace.mark("error_5xx")
        kept = trace.sampled or bool(trace.keep)
        self.recorder.push(trace, status, kept)
        if kept:
            # only kept traces pay serialization on the request path
            # (head sample + tail rules — a small fraction by design)
            self._write_sink(trace.to_dict(status, kept))

    # -- per-rank JSONL sink --------------------------------------------
    def sink_path(self) -> Optional[str]:
        if self._sink_path:
            return self._sink_path
        if not self.trace_dir:
            return None
        return os.path.join(self.trace_dir,
                            f"trace_spans_rank{self.rank}-{_PID}.jsonl")

    def _write_sink(self, trace_dict: Dict) -> None:
        path = self.sink_path()
        if path is None:
            return
        lines = []
        for s in trace_dict["spans"]:
            rec = {"kind": "trace_span"}
            rec.update(s)
            lines.append(json.dumps(rec, default=str))
        with self._sink_lock:
            if self._sink is None:
                d = os.path.dirname(os.path.abspath(path))
                os.makedirs(d, exist_ok=True)
                self._sink = open(path, "a")
            self._sink.write("\n".join(lines) + "\n")
            self._sink.flush()

    def _close_sink(self) -> None:
        with self._sink_lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except Exception:
                    pass
                self._sink = None

    def close(self) -> None:
        self._close_sink()

    # -- flight-recorder dumps ------------------------------------------
    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> Optional[str]:
        """Write the whole ring to disk (kept AND unkept traces — the
        black box's value is exactly the requests nothing chose to
        keep).  Returns the path, or None without a destination."""
        if path is None:
            if not self.trace_dir:
                return None
            path = os.path.join(
                self.trace_dir,
                f"flight_{reason}_{int(time.time() * 1e3)}.json")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        payload = {"reason": reason, "unix_s": time.time(),
                   "rank": self.rank, "pid": _PID,
                   "traces": self.recorder.snapshot()}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, default=str)
        os.replace(tmp, path)
        self.dumps.append(path)
        _log.log_info(f"trace: flight recorder dumped to {path} "
                      f"({len(payload['traces'])} traces, reason="
                      f"{reason})")
        return path

    def maybe_dump(self, reason: str) -> Optional[str]:
        """Rate-limited burst dump — the router calls this on breaker
        open / shed / partial publish.  Cheap no-op when disabled or
        without a trace_dir; the dump itself runs on a background thread
        so the request that tripped the burst never waits on ring
        serialization.  Returns the path the dump will land at."""
        if not self._enabled or not self.trace_dir:
            return None
        now = time.monotonic()
        with self._sink_lock:
            if now - self._last_dump_s < self._DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump_s = now
        path = os.path.join(
            self.trace_dir,
            f"flight_{reason}_{int(time.time() * 1e3)}.json")
        threading.Thread(target=self.dump, args=(reason, path),
                         daemon=True, name="lgbm-tpu-trace-dump").start()
        return path


# process-wide default: disabled until configure()d (CLI wires it from the
# trace_* config params; tests construct their own instances)
TRACER = Tracer()


# ---------------------------------------------------------------------------
# thread-local activation: log correlation + nested child spans without
# threading a span object through every signature
# ---------------------------------------------------------------------------
_tls = threading.local()


def current() -> Optional[TraceSpan]:
    return getattr(_tls, "span", None)


def current_trace_id() -> Optional[str]:
    s = getattr(_tls, "span", None)
    return s.trace_id if s is not None else None


class _Activation:
    """Class-based context manager (cheaper than a generator on the
    per-request hot path): makes a span the thread's active span."""

    __slots__ = ("_span", "_prev")

    def __init__(self, span):
        self._span = span

    def __enter__(self):
        if self._span is not None:
            self._prev = getattr(_tls, "span", None)
            _tls.span = self._span
        return self._span

    def __exit__(self, *exc):
        if self._span is not None:
            _tls.span = self._prev


def activate(span: Optional[TraceSpan]) -> _Activation:
    """Make ``span`` the thread's active span (None-safe no-op)."""
    return _Activation(span)


@contextmanager
def child_span(name: str, **attrs):
    """Timed child of the thread's ACTIVE span; no-op (yields None) when
    no trace is active — deep layers (trainer, gate) use this so they
    need no tracer plumbing at all."""
    parent = getattr(_tls, "span", None)
    if parent is None:
        yield None
        return
    c = parent.child(name, **attrs)
    _tls.span = c
    try:
        yield c
    finally:
        _tls.span = parent
        c.finish()


# ---------------------------------------------------------------------------
# wiring: CLI config + log/span correlation providers
# ---------------------------------------------------------------------------
def configure_from_config(config) -> Tracer:
    """Wire the process-default tracer (and the log JSON mode) from the
    ``trace_*`` config params — Application.run calls this once."""
    try:
        rank = int(os.environ.get("LIGHTGBM_TPU_RANK", "0") or 0)
    except ValueError:
        rank = 0
    TRACER.configure(enabled=bool(config.trace_requests),
                     sample_rate=config.trace_sample_rate,
                     ring=config.trace_ring,
                     trace_dir=config.trace_dir,
                     keep_slo_ms=config.trace_keep_slo_ms,
                     rank=rank)
    if config.trace_log_json:
        # enable-only: the default (False) must not clobber an
        # operator's LIGHTGBM_TPU_LOG_JSON=1 env default on every run
        _log.set_json_lines(True)
    return TRACER


# warnings/errors emitted while a trace is active carry its trace_id
# (log.py), and telemetry spans recorded inside a traced region are
# stamped with it (spans.py) — one id correlates logs, phase spans, and
# the distributed trace
_log.set_trace_provider(current_trace_id)
_spans.set_trace_id_provider(current_trace_id)
