"""Structured, nestable phase spans — the timing core of the telemetry
subsystem.

Supersedes the flat label timer (timer.py keeps ``timed``/``global_timer``
as thin shims over this module): every ``span()`` still accumulates into
the process-wide aggregate (name -> seconds/calls, printed at exit exactly
like the reference Common::Timer), and additionally — when event recording
is on — captures a structured ``Span`` event with start/duration, thread
id, the enclosing span (thread-local parent tracking), and free-form
attributes (rank, iteration, ...).  The recorded events feed the exporters
(telemetry/export.py): Chrome-trace/Perfetto timelines and the per-rank
JSONL event log.

Enablement is RUNTIME state, not import-frozen: ``set_enabled()`` flips the
timers (``LIGHTGBM_TPU_TIMETAG=1`` stays the env-var default for
back-compat), ``set_recording()`` flips event capture (``telemetry=on``
turns both on).  The disabled fast path is a single bool check.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = ["Span", "PhaseTimer", "global_timer", "span", "enabled",
           "set_enabled", "recording", "set_recording", "set_context",
           "get_context", "recorded_spans", "clear_recorded",
           "current_span", "set_trace_id_provider"]

# wall-clock epoch matching perf_counter 0, so exported timestamps are
# absolute while in-process math stays on the monotonic clock
_EPOCH = time.time() - time.perf_counter()


class PhaseTimer:
    """name -> accumulated seconds (reference Common::Timer::Print
    semantics); the aggregate view every span feeds."""

    def __init__(self):
        self._lock = threading.Lock()
        self.acc: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self.acc[name] = self.acc.get(name, 0.0) + seconds
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> str:
        lines = ["LightGBM-TPU phase timers:"]
        for name in sorted(self.acc, key=lambda k: -self.acc[k]):
            lines.append(f"  {name}: {self.acc[name]:.3f}s "
                         f"({self.counts[name]} calls)")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self.acc.clear()
            self.counts.clear()


global_timer = PhaseTimer()

# exact historical truthiness (any non-empty value except "0" enables)
_enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")
_recording = False
_MAX_RECORDED = 65536          # bounded: sustained traffic must not OOM

_ids = itertools.count(1)
_tls = threading.local()
_ctx_lock = threading.Lock()
_context: Dict[str, Any] = {}   # process-wide attrs stamped on every span

# distributed-trace correlation: telemetry/trace.py registers a provider
# returning the thread's active trace id, and recorded spans carry it as
# an attribute — only consulted when event recording is on, so the plain
# timer fast path never pays the lookup
_TRACE_ID_PROVIDER = None


def set_trace_id_provider(fn) -> None:
    global _TRACE_ID_PROVIDER
    _TRACE_ID_PROVIDER = fn


class Span:
    """One finished (or in-flight) timed region."""

    __slots__ = ("id", "name", "start_s", "dur_s", "thread_id", "parent_id",
                 "parent_name", "attrs")

    def __init__(self, name: str, parent: Optional["Span"],
                 attrs: Dict[str, Any]):
        self.id = next(_ids)
        self.name = name
        self.start_s = time.perf_counter()
        self.dur_s = 0.0
        self.thread_id = threading.get_ident()
        self.parent_id = parent.id if parent is not None else None
        self.parent_name = parent.name if parent is not None else None
        self.attrs = attrs

    @property
    def start_unix_s(self) -> float:
        return self.start_s + _EPOCH

    def to_dict(self) -> Dict[str, Any]:
        return {"id": self.id, "name": self.name,
                "start_unix_s": self.start_unix_s, "dur_s": self.dur_s,
                "thread_id": self.thread_id, "parent_id": self.parent_id,
                "parent_name": self.parent_name, "attrs": dict(self.attrs)}


class _Recorder:
    """Bounded ring of finished spans (drop-newest once full, with a
    dropped counter so truncation is visible, never silent)."""

    def __init__(self, capacity: int = _MAX_RECORDED):
        self._lock = threading.Lock()
        self._cap = capacity
        self._spans: List[Span] = []
        self.dropped = 0

    def record(self, s: Span) -> None:
        with self._lock:
            if len(self._spans) >= self._cap:
                self.dropped += 1
                return
            self._spans.append(s)

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0


recorder = _Recorder()


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    """Runtime switch for the phase timers (tests and ``telemetry=on`` flip
    it without re-importing; LIGHTGBM_TPU_TIMETAG only sets the default)."""
    global _enabled
    _enabled = bool(value)


def recording() -> bool:
    return _recording


def set_recording(value: bool) -> None:
    global _recording
    _recording = bool(value)


def set_context(**attrs) -> None:
    """Merge process-wide attributes (e.g. rank) stamped on every span;
    ``set_context(rank=None)`` removes a key."""
    with _ctx_lock:
        for k, v in attrs.items():
            if v is None:
                _context.pop(k, None)
            else:
                _context[k] = v


def get_context() -> Dict[str, Any]:
    with _ctx_lock:
        return dict(_context)


def _stack() -> List[Span]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional[Span]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def recorded_spans() -> List[Span]:
    return recorder.snapshot()


def clear_recorded() -> None:
    recorder.clear()


@contextmanager
def span(name: str, sync=None, **attrs):
    """Time a region under `name` when timers are enabled.

    sync: optional array/pytree to block_until_ready before stopping the
    clock, so async-dispatched device work is attributed to the phase that
    launched it instead of whoever syncs next.  Extra kwargs become span
    attributes (merged over the process-wide context)."""
    if not _enabled:
        yield None
        return
    stack = _stack()
    merged = get_context()
    merged.update(attrs)
    if _recording and _TRACE_ID_PROVIDER is not None:
        tid = _TRACE_ID_PROVIDER()
        if tid is not None:
            merged.setdefault("trace_id", tid)
    s = Span(name, stack[-1] if stack else None, merged)
    stack.append(s)
    try:
        yield s
    finally:
        stack.pop()
        if sync is not None:
            import jax
            jax.block_until_ready(sync)
        s.dur_s = time.perf_counter() - s.start_s
        global_timer.add(name, s.dur_s)
        if _recording:
            recorder.record(s)
