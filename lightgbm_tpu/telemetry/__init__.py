"""Unified telemetry subsystem: spans, metrics registry, exporters,
per-iteration training stats.

One observability layer for the whole system, absorbing the ad-hoc pieces
that grew alongside it (the flat phase timer, serving-only counters,
dataset setup timings, checkpoint overhead probes):

- ``spans`` — structured, nestable phase spans with thread-local parent
  tracking, optional device-sync duration, and free-form attributes
  (rank/iteration); ``timer.timed``/``timer.global_timer`` are thin compat
  shims over it.
- ``registry`` — process-wide metrics registry (counters, gauges,
  fixed-bucket histograms with percentile reads); ``ServingMetrics``
  re-registers its per-model counters into one instead of owning dicts.
- ``training`` — per-iteration training stats (grad/grow/apply actuals,
  staged-probe hist/split/partition decomposition, measured collective
  probe, compile deltas) wired through GBDT and surfaced via
  ``Booster.telemetry_stats()`` / the ``record_telemetry`` callback.
- ``export`` — Prometheus text format (served at
  ``GET /v1/metrics/prometheus``), Chrome-trace/Perfetto span timelines,
  and the per-rank JSONL event log + cluster rollup.

Config surface: ``telemetry=on|off`` (default off — the fused train step
stays fused and span overhead is one bool check), ``telemetry_dir`` (JSONL
+ trace output, one file per rank), ``profile_dir`` +
``profile_iterations`` (jax.profiler device traces around chosen
iterations).  ``LIGHTGBM_TPU_TIMETAG=1`` remains the env alias for the
phase timers alone.

``training`` is imported lazily (it pulls the tree-learner stack); spans,
registry, and export are light.
"""

from . import spans
from .registry import (Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
                       get_counter)
from .export import (JsonlEventLog, assemble_traces, chrome_trace,
                     prometheus_text, read_trace_spans,
                     rollup_telemetry_dir, trace_chrome_trace,
                     write_chrome_trace, write_trace_chrome_trace)
from .spans import span, set_enabled, set_recording, set_context
from . import trace
from .trace import TRACER, Tracer

__all__ = ["spans", "span", "set_enabled", "set_recording", "set_context",
           "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "get_counter",
           "prometheus_text", "chrome_trace", "write_chrome_trace",
           "JsonlEventLog", "rollup_telemetry_dir",
           "trace", "TRACER", "Tracer", "assemble_traces",
           "read_trace_spans", "trace_chrome_trace",
           "write_trace_chrome_trace"]


def __getattr__(name):
    if name == "training":
        from . import training as _training
        return _training
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
