"""Per-iteration training statistics: honest per-stage attribution.

The production grower is ONE jitted XLA program (tree_learner.py), so a
host clock cannot see inside it — and per-stage numbers that are guesses
are worse than none (PROFILE_r05: when the chip is flaky, honest
attribution is the scarcest resource).  This module therefore reports two
kinds of numbers, clearly separated:

- **Actuals**, measured around real host boundaries of the production
  path: ``grad_s`` (gradient computation), ``grow_s`` (the whole grower
  program, device-synced), ``apply_s`` (state->tree conversion + score
  update), ``iter_s``, ``checkpoint_s`` (engine save time), and XLA
  compile count/seconds deltas (via jax.monitoring backend-compile
  events).  Telemetry disables the fused train step — per-stage
  attribution needs the host boundaries the fused path deliberately
  removes, which is exactly why ``telemetry=off`` is the perf default.

- **Staged-probe decompositions**: ``hist_s`` / ``split_s`` /
  ``partition_s`` come from re-growing the iteration's tree with the SAME
  device ops (build_histogram / find_best_split / partition) staged as
  separate jitted programs with a sync after each — a real measurement of
  real work on the real data, following the dense-grower decomposition
  (one masked both-children histogram pass per split).  The probe's tree
  is discarded; the production model is untouched.  ``comm_s`` is a
  measured collective probe: one psum of the iteration's histogram shape
  on the learner's actual mesh, scaled by the number of histogram
  reductions the iteration performed (data/voting-parallel).  Unsupported
  configurations (forced splits, CEGB lazy, interaction constraints,
  extra_trees, per-node column sampling, parallel learners for the staged
  part) report ``None`` for the probe keys rather than a fabricated 0.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import spans
from .registry import REGISTRY

__all__ = ["TrainingTelemetry", "maybe_training_telemetry",
           "compile_tracker", "compile_snapshot", "PHASE_KEYS",
           "hist_path_of"]

PHASE_KEYS = ("grad_s", "grow_s", "hist_s", "split_s", "partition_s",
              "comm_s", "apply_s", "checkpoint_s")

_ITER_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0,
                 60.0)

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class _CompileTracker:
    """Counts XLA backend compiles + seconds via jax.monitoring duration
    events; process-wide (listeners cannot be unregistered, so exactly one
    is ever installed)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._installed = False
        self.count = 0
        self.seconds = 0.0

    def install(self) -> None:
        with self._lock:
            if self._installed:
                return
            self._installed = True
        try:
            import jax.monitoring as _monitoring

            def _on_duration(event, duration, **kwargs):
                if event == _COMPILE_EVENT:
                    with self._lock:
                        self.count += 1
                        self.seconds += float(duration)

            _monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:    # monitoring API drift: compiles report as 0
            pass

    def snapshot(self):
        with self._lock:
            return self.count, self.seconds


compile_tracker = _CompileTracker()


def compile_snapshot():
    """(count, seconds) snapshot of the process-wide XLA backend-compile
    tracker, installing the listener on first use so DELTAS work even when
    telemetry=off.  The continuous trainer brackets each cycle with this
    to export per-cycle compile counts — the "steady-state cycles compile
    nothing" evidence for bucketed incremental training."""
    compile_tracker.install()
    return compile_tracker.snapshot()


def maybe_training_telemetry(config) -> Optional["TrainingTelemetry"]:
    """Create the per-iteration collector when ``telemetry=on``; also flips
    the span timers on (the config-driven equivalent of
    LIGHTGBM_TPU_TIMETAG).  Span EVENT recording — which buffers Span
    objects for the JSONL/Chrome-trace exporters — only turns on when a
    ``telemetry_dir`` will actually consume them: without a consumer the
    process-global recorder would silently buffer every later span
    (serving hot paths included) up to its cap for the process lifetime."""
    if not getattr(config, "telemetry", False):
        return None
    spans.set_enabled(True)
    if getattr(config, "telemetry_dir", ""):
        spans.set_recording(True)
    compile_tracker.install()
    return TrainingTelemetry()


def hist_path_of(learner) -> str:
    """Label of the ACTIVE histogram path, attached to every per-iteration
    record and the staged probe so ``hist_s`` comparisons across configs
    are never apples-to-oranges: ``f32``/``bf16`` (contraction input dtype)
    for the standard engine, ``int16x32`` for fixed-point accumulation
    (config ``quantized_histograms``), ``+packed`` appended when the device
    bin matrix is sub-byte packed."""
    cfg = learner.grower_cfg
    if getattr(cfg, "quantized", False):
        label = "int16x32"
        if getattr(cfg, "pack_spec", ()):
            label += "+packed"
        return label
    return "bf16" if cfg.hist_dtype == "bfloat16" else "f32"


# ---------------------------------------------------------------------------
# Staged probe: the dense-grower decomposition as separate jitted programs
# ---------------------------------------------------------------------------
def _staged_probe_supported(learner) -> bool:
    from ..tree_learner import SerialTreeLearner
    cfg = learner.grower_cfg
    return (type(learner) is SerialTreeLearner
            and getattr(learner, "forced", None) is None
            and not cfg.use_cegb_lazy
            and not cfg.use_interaction
            and not cfg.extra_trees
            # any column sampling: the probe's all-ones mask would grow a
            # DIFFERENT tree than production and misreport its phase times
            and learner.config.feature_fraction >= 1.0
            and cfg.feature_fraction_bynode >= 1.0
            and not (cfg.use_monotone
                     and cfg.monotone_method in ("intermediate", "advanced"))
            and getattr(learner.dataset, "device_bins", None) is not None)


def _jits():
    """Lazily build the staged jitted programs (jax import deferred so
    merely importing telemetry never initializes a backend)."""
    global _STAGE
    if _STAGE is not None:
        return _STAGE
    import jax
    import jax.numpy as jnp
    from ..ops.histogram import build_histogram, quantize_grad_hess
    from ..tree_learner import (_apply_split_bookkeeping, _child_weights,
                                _init_tree_state, _scan_leaf, _store_best)
    from ..ops.split import dequantize_hist, leaf_output

    # quantized configs (hist_path int16x32[+packed]): the probe's weights
    # are pre-quantized int16 and ``bins`` is the learner's ACTIVE matrix
    # (the packed planes when packing is on), so hist_s times the real
    # fixed-point contraction; histograms are dequantized on the way out so
    # the split/partition stages run the shared f32 program.
    @jax.jit
    def quantize(grad_m, hess_m, mask, quant_bounds):
        n_total = jnp.asarray(grad_m.shape[0], jnp.float32)
        return quantize_grad_hess(grad_m, hess_m, mask, n_total,
                                  quant_bounds)

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def root_hist(cfg, bins, grad_m, hess_m, mask, hist_layout, scale3):
        h = build_histogram(
            bins, jnp.stack([grad_m, hess_m, mask], axis=1), cfg.num_bins,
            impl=cfg.hist_impl, hist_dtype=cfg.hist_dtype,
            layout=hist_layout, widths=cfg.hist_widths,
            pack_spec=cfg.pack_spec)
        return dequantize_hist(h, scale3)

    @functools.partial(jax.jit, static_argnames=("cfg", "n", "f"))
    def root_scan(cfg, rhist, num_bins_f, has_missing_f, fmask, monotone,
                  is_cat_f, bmap, gain_scale_f, n, f):
        root_sums = rhist[0].sum(axis=0)
        root_out = leaf_output(root_sums[0], root_sums[1], cfg.lambda_l1,
                               cfg.lambda_l2, cfg.max_delta_step)
        state = _init_tree_state(cfg, n, root_sums.dtype, root_out,
                                 root_sums, f)
        res = _scan_leaf(rhist, root_sums, jnp.int32(0), cfg, num_bins_f,
                         has_missing_f, fmask, monotone, is_cat_f, bmap,
                         gain_scale_f=gain_scale_f)
        return _store_best(state, 0, res)

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def partition(cfg, state, bins, num_bins_f, has_missing_f, monotone,
                  bmap, pack_map):
        best_leaf = jnp.argmax(state.best_gain).astype(jnp.int32)
        gain = state.best_gain[best_leaf]
        new_leaf = state.n_leaves
        feat = state.best_feature[best_leaf]
        thr = state.best_threshold[best_leaf]
        dleft = state.best_default_left[best_leaf]
        split_cat = (state.best_is_cat[best_leaf]
                     if cfg.use_categorical else jnp.asarray(False))
        cat_mask = state.best_cat_mask[best_leaf]
        from ..ops.histogram import take_device_column
        if cfg.use_efb:
            from ..efb import decode_member_bin
            col = take_device_column(bins, bmap.bundle_of_f[feat], pack_map)
            fcol = decode_member_bin(col, bmap.offset_of_f[feat],
                                     num_bins_f[feat])
        else:
            fcol = take_device_column(bins, feat, pack_map)
        missing_bin = num_bins_f[feat] - 1
        is_missing = has_missing_f[feat] & (fcol == missing_bin)
        go_left = jnp.where(is_missing, dleft, fcol <= thr)
        if cfg.use_categorical:
            go_left = jnp.where(split_cat, cat_mask[fcol], go_left)
        in_leaf = state.row_leaf == best_leaf
        row_leaf = jnp.where(in_leaf & ~go_left, new_leaf, state.row_leaf)
        state = _apply_split_bookkeeping(
            state, best_leaf, gain, feat, thr, dleft, split_cat, cat_mask,
            cfg, monotone)._replace(row_leaf=row_leaf)
        return state, best_leaf, new_leaf

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def child_hists(cfg, bins, row_leaf, best_leaf, new_leaf, grad_m,
                    hess_m, mask, hist_layout, scale3):
        left_m = (row_leaf == best_leaf).astype(grad_m.dtype)
        right_m = (row_leaf == new_leaf).astype(grad_m.dtype)
        h6 = build_histogram(
            bins, _child_weights(grad_m, hess_m, mask, left_m, right_m),
            cfg.num_bins, impl=cfg.hist_impl, hist_dtype=cfg.hist_dtype,
            layout=hist_layout, widths=cfg.hist_widths,
            pack_spec=cfg.pack_spec)
        h6 = dequantize_hist(h6, scale3)
        return h6[..., 0:3], h6[..., 3:6]

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def scan(cfg, state, hist_l, hist_r, best_leaf, new_leaf, num_bins_f,
             has_missing_f, fmask, monotone, is_cat_f, bmap, gain_scale_f):
        depth = state.leaf_depth[best_leaf]   # bookkeeping already advanced
        res_l = _scan_leaf(hist_l, state.leaf_sum[best_leaf], depth, cfg,
                           num_bins_f, has_missing_f, fmask, monotone,
                           is_cat_f, bmap,
                           bounds=(state.leaf_lo[best_leaf],
                                   state.leaf_hi[best_leaf]),
                           gain_scale_f=gain_scale_f)
        res_r = _scan_leaf(hist_r, state.leaf_sum[new_leaf], depth, cfg,
                           num_bins_f, has_missing_f, fmask, monotone,
                           is_cat_f, bmap,
                           bounds=(state.leaf_lo[new_leaf],
                                   state.leaf_hi[new_leaf]),
                           gain_scale_f=gain_scale_f)
        state = _store_best(state, best_leaf, res_l)
        return _store_best(state, new_leaf, res_r)

    _STAGE = {"root_hist": root_hist, "root_scan": root_scan,
              "partition": partition, "child_hists": child_hists,
              "scan": scan, "quantize": quantize}
    return _STAGE


_STAGE = None


def run_staged_probe(learner, grad, hess, mask,
                     timings: Optional[Dict[str, float]] = None
                     ) -> Optional[Dict[str, float]]:
    """Re-grow one tree from (grad, hess, mask) with each phase as its own
    synced device program; returns accumulated {hist_s, split_s,
    partition_s, probe_steps}.  The grown tree is discarded — the
    production model never sees the probe."""
    if not _staged_probe_supported(learner):
        return None
    import jax
    import jax.numpy as jnp
    from ..ops.split import K_EPSILON
    stage = _jits()
    ds = learner.dataset
    cfg = learner.grower_cfg._replace(parallel_mode="none", axis_name=None)
    # the learner's ACTIVE bin matrix: the packed byte planes when the
    # quantized engine packed them, else the plain device matrix — hist_s
    # must time the path production actually runs (hist_path_of labels it)
    bins = getattr(learner, "train_bins", None)
    if bins is None:
        bins = ds.device_bins
    pack_map = getattr(learner, "pack_map", None)
    n = int(bins.shape[0])
    f = int(np.asarray(ds.num_bins_per_feature).shape[0])
    # all-ones feature mask on purpose: calling learner.feature_mask()
    # here would advance its column-sampling RNG and change the MODEL —
    # the probe must be observation-only
    fmask = jnp.ones((f,), bool)
    grad_m = grad * mask
    hess_m = hess * mask
    count_m = mask
    scale3 = None
    layout = learner.hist_layout
    out = timings if timings is not None else {}
    for k in ("hist_s", "split_s", "partition_s"):
        out.setdefault(k, 0.0)
    out.setdefault("probe_steps", 0)

    def timed_call(key, fn, *args, **kwargs):
        t0 = time.perf_counter()
        res = fn(*args, **kwargs)
        jax.block_until_ready(res)
        out[key] += time.perf_counter() - t0
        return res

    if cfg.quantized:
        # the runtime-max bounds fallback keeps the probe self-contained
        # (the booster's objective-derived bounds only tighten the scale)
        grad_m, hess_m, count_m, scale3, _clips = timed_call(
            "hist_s", stage["quantize"], grad_m, hess_m, mask, None)
    rhist = timed_call("hist_s", stage["root_hist"], cfg, bins, grad_m,
                       hess_m, count_m, layout, scale3)
    state = timed_call("split_s", stage["root_scan"], cfg, rhist,
                       ds.num_bins_per_feature, ds.has_missing_per_feature,
                       fmask, learner.monotone, learner.is_cat_f,
                       learner.bmap, learner.gain_scale, n, f)
    for _ in range(cfg.num_leaves - 1):
        if float(jnp.max(state.best_gain)) <= K_EPSILON:
            break
        state, bl, nl = timed_call(
            "partition_s", stage["partition"], cfg, state, bins,
            ds.num_bins_per_feature, ds.has_missing_per_feature,
            learner.monotone, learner.bmap, pack_map)
        hist_l, hist_r = timed_call(
            "hist_s", stage["child_hists"], cfg, bins, state.row_leaf, bl,
            nl, grad_m, hess_m, count_m, layout, scale3)
        state = timed_call(
            "split_s", stage["scan"], cfg, state, hist_l, hist_r, bl, nl,
            ds.num_bins_per_feature, ds.has_missing_per_feature, fmask,
            learner.monotone, learner.is_cat_f, learner.bmap,
            learner.gain_scale)
        out["probe_steps"] += 1
    return out


# ---------------------------------------------------------------------------
# Collective probe: one real psum of the histogram shape on the real mesh
# ---------------------------------------------------------------------------
class _CommProbe:
    def __init__(self, mesh, axis: str, shape):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import compat_shard_map
        ndev = int(mesh.devices.size)
        spec = P(axis, *([None] * len(shape)))

        def psum_local(x):
            return jax.lax.psum(x, axis)

        self._fn = jax.jit(compat_shard_map(
            psum_local, mesh=mesh, in_specs=(spec,), out_specs=spec))
        self._x = jax.device_put(
            jnp.ones((ndev,) + tuple(shape), jnp.float32),
            NamedSharding(mesh, spec))
        self._fn(self._x).block_until_ready()     # compile outside the clock

    def measure(self) -> float:
        import jax
        t0 = time.perf_counter()
        jax.block_until_ready(self._fn(self._x))
        return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# The per-iteration collector GBDT drives
# ---------------------------------------------------------------------------
class TrainingTelemetry:
    """Collects one record per boosting iteration; attached to a GBDT when
    ``telemetry=on``.  Records are plain dicts (JSON-ready) — the engine
    streams them to the per-rank JSONL log and ``Booster.telemetry_stats``
    exposes them to callers/callbacks."""

    def __init__(self, probe: bool = True, probe_every: int = 1):
        self.records: List[Dict] = []
        self.probe_enabled = probe
        self.probe_every = max(int(probe_every), 1)
        # ACTIVE histogram-path label (hist_path_of): set by the booster
        # once the learner exists; stamped on every record + the summary
        self.hist_path: Optional[str] = None
        # trees grown per iteration (objective num_model_per_iteration):
        # stamped on records so per-iteration times across multiclass vs
        # binary runs are never compared per-tree by accident
        self.num_class: int = 1
        self._cur: Optional[Dict] = None
        self._t0 = 0.0
        self._span_cm = None
        self._probe_warmed = False
        self._comm_probe: Optional[_CommProbe] = None
        self._comm_probe_key = None
        self._c_iters = REGISTRY.counter(
            "lgbm_train_iterations_total", "boosting iterations completed")
        self._h_iter = REGISTRY.histogram(
            "lgbm_train_iteration_seconds", "wall time per boosting "
            "iteration", buckets=_ITER_BUCKETS)

    # -- iteration lifecycle -------------------------------------------
    def start_iteration(self, iteration: int) -> None:
        if self._cur is not None:      # unbalanced start: close the old one
            self.finish_iteration()
        cc, cs = compile_tracker.snapshot()
        self._cur = {"iteration": int(iteration),
                     "grad_s": 0.0, "grow_s": 0.0, "apply_s": 0.0,
                     "comm_s": 0.0, "checkpoint_s": 0.0,
                     "hist_s": None, "split_s": None, "partition_s": None,
                     "hist_path": self.hist_path,
                     "num_class": int(self.num_class),
                     "_cc": cc, "_cs": cs}
        self._t0 = time.perf_counter()
        self._span_cm = spans.span("train::iteration", iteration=iteration)
        self._span_cm.__enter__()

    def add(self, key: str, seconds: float) -> None:
        if self._cur is not None:
            base = self._cur.get(key)
            self._cur[key] = (base or 0.0) + float(seconds)

    def finish_iteration(self) -> None:
        cur, self._cur = self._cur, None
        if cur is None:
            return
        if self._span_cm is not None:
            self._span_cm.__exit__(None, None, None)
            self._span_cm = None
        cur["iter_s"] = time.perf_counter() - self._t0
        cc, cs = compile_tracker.snapshot()
        cur["compile_count"] = cc - cur.pop("_cc")
        cur["compile_s"] = round(cs - cur.pop("_cs"), 6)
        self.records.append(cur)
        self._c_iters.inc()
        self._h_iter.observe(cur["iter_s"])

    def annotate_last(self, key: str, seconds: float) -> None:
        """Attach a post-iteration cost (engine checkpoint save) to the
        most recent record."""
        if self.records:
            self.records[-1][key] = (self.records[-1].get(key) or 0.0) \
                + float(seconds)

    # -- probes ---------------------------------------------------------
    def probe(self, learner, grad, hess, mask) -> None:
        if not self.probe_enabled or self._cur is None:
            return
        if self._cur["iteration"] % self.probe_every != 0:
            return
        if not self._probe_warmed:
            # first call pays the staged programs' compiles; run once
            # untimed so compile time never masquerades as phase time
            run_staged_probe(learner, grad, hess, mask, timings={})
            self._probe_warmed = True
        timings = {k: v for k, v in self._cur.items()
                   if k in ("hist_s", "split_s", "partition_s")
                   and v is not None}
        res = run_staged_probe(learner, grad, hess, mask, timings=timings)
        if res is not None:
            self._cur.update({k: res[k] for k in
                              ("hist_s", "split_s", "partition_s")})
            self._cur["probe_steps"] = res["probe_steps"]

    def comm(self, learner, n_hist_reductions: int) -> None:
        """Measured collective probe for parallel learners: one psum of
        the histogram shape on the learner's mesh, scaled by the number of
        histogram reductions this iteration performed (root + one per
        split for data-parallel; voting's elected-feature psums are
        approximated with the same shape).  Data/voting only: the
        feature-parallel learner performs no histogram reductions (its
        comm is tiny split-decision exchanges), so a histogram-shaped
        probe would fabricate a comm_s it never pays."""
        from ..parallel.data_parallel import DataParallelTreeLearner
        if not isinstance(learner, DataParallelTreeLearner):
            return
        mesh = getattr(learner, "mesh", None)
        ax = getattr(learner, "AXIS", None)
        if mesh is None or ax is None or self._cur is None:
            return
        if int(mesh.devices.size) <= 1:
            return
        try:
            g = int(getattr(learner, "sharded_bins").shape[1])
        except AttributeError:
            g = int(np.asarray(
                learner.dataset.num_bins_per_feature).shape[0])
        shape = (g, int(learner.grower_cfg.num_bins), 3)
        key = (id(mesh), shape)
        try:
            if self._comm_probe is None or self._comm_probe_key != key:
                self._comm_probe = _CommProbe(mesh, ax, shape)
                self._comm_probe_key = key
            per_psum = self._comm_probe.measure()
        except Exception:
            # a mesh the probe cannot drive (API drift, feature-parallel
            # layouts) must not take training down; comm stays unreported
            self._cur["comm_s"] = None
            return
        self.add("comm_s", per_psum * max(int(n_hist_reductions), 0))

    # -- summaries ------------------------------------------------------
    def summary(self) -> Dict:
        recs = self.records
        out: Dict = {"iterations": len(recs)}
        if not recs:
            return out

        def mean(key):
            vals = [r[key] for r in recs
                    if isinstance(r.get(key), (int, float))]
            return (sum(vals) / len(vals)) if vals else None

        for key in ("iter_s",) + PHASE_KEYS:
            out[key] = mean(key)
        out["hist_path"] = self.hist_path
        out["num_class"] = int(self.num_class)
        out["compile_count"] = sum(int(r.get("compile_count") or 0)
                                   for r in recs)
        out["compile_s"] = round(sum(float(r.get("compile_s") or 0.0)
                                     for r in recs), 6)
        return out
