"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

One registry instance holds get-or-create instrument families keyed by
(metric name, label set) — the Prometheus data model, kept deliberately
tiny (no external client library; the exposition format lives in
telemetry/export.py).  ``REGISTRY`` is the process-wide default that
training telemetry publishes into; serving builds one registry per
``ServingMetrics`` (per app) so independent front-ends — and tests — don't
share counter state, and the HTTP exporter dumps both.

Histograms use fixed upper-bound buckets with linear interpolation inside
the winning bucket for percentile reads — O(buckets) memory under any
traffic, the standard trade against exact quantiles.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "DEFAULT_LATENCY_BUCKETS", "get_counter"]

# seconds; spans request latencies from sub-ms device calls to stragglers
DEFAULT_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                           0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Counter:
    """Monotonically increasing value."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Set-to-current value."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with percentile reads.

    ``buckets`` are inclusive upper bounds; an implicit +inf bucket catches
    the tail.  ``percentile(p)`` interpolates linearly inside the bucket
    holding the p-th observation (the +inf bucket reports its lower edge —
    a deliberate under-estimate rather than an invented tail)."""

    def __init__(self, buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS):
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = bs
        self._counts = [0] * (len(bs) + 1)     # +1 = the +inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        idx = len(self._bounds)
        for i, b in enumerate(self._bounds):
            if value <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound, count) pairs, Prometheus ``le`` style,
        ending with (+inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for b, c in zip(self._bounds, counts):
            cum += c
            out.append((b, cum))
        out.append((math.inf, cum + counts[-1]))
        return out

    def percentile(self, p: float) -> float:
        """p in [0, 100]; 0.0 when empty."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        target = max(p, 0.0) / 100.0 * total
        cum = 0
        for i, c in enumerate(counts):
            if cum + c >= target and c > 0:
                lo = self._bounds[i - 1] if i > 0 else 0.0
                if i >= len(self._bounds):     # +inf bucket: report its edge
                    return self._bounds[-1]
                hi = self._bounds[i]
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self._bounds[-1]

    def snapshot(self) -> Dict[str, float]:
        return {"count": self.count, "sum": self.sum,
                "p50": self.percentile(50.0), "p95": self.percentile(95.0),
                "p99": self.percentile(99.0)}


class _Family:
    def __init__(self, kind: str, help_text: str):
        self.kind = kind
        self.help = help_text
        self.instruments: Dict[Tuple[Tuple[str, str], ...], object] = {}


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Get-or-create instrument store; same (name, labels) returns the SAME
    instrument, so re-registration is idempotent and cheap."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get(self, kind: str, name: str, help_text: str, labels: Dict,
             factory):
        key = _label_key(labels or {})
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(kind, help_text)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"cannot re-register as {kind}")
            inst = fam.instruments.get(key)
            if inst is None:
                inst = fam.instruments[key] = factory()
            return inst

    def counter(self, name: str, help_text: str = "", **labels) -> Counter:
        return self._get("counter", name, help_text, labels, Counter)

    def gauge(self, name: str, help_text: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help_text, labels, Gauge)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[Iterable[float]] = None,
                  **labels) -> Histogram:
        return self._get(
            "histogram", name, help_text, labels,
            lambda: Histogram(buckets or DEFAULT_LATENCY_BUCKETS))

    def collect(self):
        """[(name, kind, help, [(labels_dict, instrument), ...])], sorted by
        name — the exporter's stable iteration order."""
        with self._lock:
            fams = sorted(self._families.items())
            out = []
            for name, fam in fams:
                rows = [(dict(key), inst)
                        for key, inst in sorted(fam.instruments.items())]
                out.append((name, fam.kind, fam.help, rows))
        return out

    def snapshot(self) -> Dict:
        """Plain-dict view (JSON-friendly) for tests and debug endpoints."""
        out: Dict = {}
        for name, kind, _help, rows in self.collect():
            fam: Dict = {}
            for labels, inst in rows:
                key = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                fam[key or "_"] = (inst.snapshot()
                                   if isinstance(inst, Histogram)
                                   else inst.value)
            out[name] = fam
        return out


REGISTRY = MetricsRegistry()


def get_counter(registry: Optional[MetricsRegistry], name: str,
                help_text: str = "") -> Counter:
    """Counter on ``registry``, or on the process-global ``REGISTRY``
    when None — the default-wiring convenience components with an
    optional ``metrics_registry`` parameter share."""
    return (registry if registry is not None else REGISTRY).counter(
        name, help_text)
