"""Telemetry exporters: Prometheus text format, Chrome-trace JSON, and the
per-rank JSONL event log + job-level rollup.

Three views of the same data, one per consumer:

- ``prometheus_text`` renders one or more MetricsRegistry instances in the
  Prometheus exposition format; the serving front-end serves it at
  ``GET /v1/metrics/prometheus`` (additive — ``/v1/metrics`` stays JSON).
- ``chrome_trace``/``write_chrome_trace`` turn recorded spans into a
  Chrome-trace/Perfetto ``traceEvents`` timeline (load in ui.perfetto.dev
  or chrome://tracing; device-level traces come from ``profile_dir`` /
  xprof instead).
- ``JsonlEventLog`` appends one JSON object per line (iteration stats,
  span dumps, summaries) to a per-rank file; ``rollup_telemetry_dir``
  aggregates every rank's file into a job-level summary — the shape
  ``cluster.train_distributed`` writes on exit, append-mode so supervised
  restarts accumulate into the same per-rank files.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Dict, Iterable, List, Optional

from .registry import Histogram, MetricsRegistry
from . import spans as _spans

__all__ = ["prometheus_text", "chrome_trace", "write_chrome_trace",
           "JsonlEventLog", "rank_jsonl_path", "rollup_telemetry_dir",
           "read_trace_spans", "assemble_traces", "trace_chrome_trace",
           "write_trace_chrome_trace"]


# ---------------------------------------------------------------------------
# Prometheus text format
# ---------------------------------------------------------------------------
def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(*registries: MetricsRegistry) -> str:
    """Render registries in the Prometheus exposition format (duplicates —
    e.g. the global registry passed twice — are emitted once)."""
    lines: List[str] = []
    seen_regs, seen_names = set(), set()
    for reg in registries:
        if reg is None or id(reg) in seen_regs:
            continue
        seen_regs.add(id(reg))
        for name, kind, help_text, rows in reg.collect():
            if name in seen_names:      # same family from two registries:
                continue                # first (app-local) one wins
            seen_names.add(name)
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, inst in rows:
                if isinstance(inst, Histogram):
                    for le, cum in inst.bucket_counts():
                        le_attr = 'le="' + _fmt_value(le) + '"'
                        lines.append(
                            f"{name}_bucket{_fmt_labels(labels, le_attr)}"
                            f" {cum}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(labels)} "
                        f"{_fmt_value(inst.sum)}")
                    lines.append(
                        f"{name}_count{_fmt_labels(labels)} {inst.count}")
                else:
                    lines.append(f"{name}{_fmt_labels(labels)} "
                                 f"{_fmt_value(inst.value)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Chrome trace (Perfetto-loadable)
# ---------------------------------------------------------------------------
def chrome_trace(span_list: Optional[Iterable[_spans.Span]] = None) -> Dict:
    """Recorded spans -> Chrome-trace dict ({"traceEvents": [...]})."""
    if span_list is None:
        span_list = _spans.recorded_spans()
    pid = os.getpid()
    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
               "args": {"name": "lightgbm_tpu"}}]
    for s in span_list:
        events.append({
            "name": s.name, "ph": "X", "pid": pid, "tid": s.thread_id,
            # trace timestamps are microseconds
            "ts": s.start_unix_s * 1e6, "dur": s.dur_s * 1e6,
            "args": dict(s.attrs),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str,
                       span_list: Optional[Iterable[_spans.Span]] = None
                       ) -> str:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(chrome_trace(span_list), fh)
    return path


# ---------------------------------------------------------------------------
# Distributed-trace collector (telemetry/trace.py span sinks)
# ---------------------------------------------------------------------------
def read_trace_spans(trace_dir: str) -> List[Dict]:
    """Every trace span recorded under ``trace_dir`` (recursive glob over
    the per-rank ``trace_spans_rank*.jsonl`` sinks — a fleet's processes
    may each own a subdirectory).  Torn lines from killed workers are
    skipped, same policy as the telemetry rollup."""
    import glob
    out: List[Dict] = []
    # "**" matches zero path segments too, so one recursive glob covers
    # both top-level rank files and per-process subdirectories
    pattern = os.path.join(trace_dir, "**", "trace_spans_rank*.jsonl")
    for path in sorted(glob.glob(pattern, recursive=True)):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "trace_span" and rec.get("trace_id"):
                    out.append(rec)
    return out


def assemble_traces(spans_or_dir) -> Dict[str, List[Dict]]:
    """Group spans by trace_id (the cross-process assembly step): accepts
    a trace_dir or an iterable of span dicts, returns
    ``{trace_id: [span, ...]}`` with each trace's spans sorted by start
    time — one request's full causal chain across every process that
    recorded a piece of it."""
    spans = (read_trace_spans(spans_or_dir)
             if isinstance(spans_or_dir, str) else list(spans_or_dir))
    traces: Dict[str, List[Dict]] = {}
    for s in spans:
        traces.setdefault(str(s["trace_id"]), []).append(s)
    for tid in traces:
        traces[tid].sort(key=lambda s: (float(s.get("start_unix_s", 0.0)),
                                        str(s.get("span_id", ""))))
    return traces


def trace_chrome_trace(spans: Iterable[Dict]) -> Dict:
    """Assembled trace spans -> one Chrome-trace/Perfetto dict.  Each
    RANK renders as a process row (pid = rank, so cross-process hops are
    visually stacked), threads within a rank as tracks; span attributes
    (replica picked, breaker state, version) land in ``args``."""
    spans = sorted(spans, key=lambda s: float(s.get("start_unix_s", 0.0)))
    ranks = sorted({int(s.get("rank", 0)) for s in spans})
    events: List[Dict] = [
        {"name": "process_name", "ph": "M", "pid": r, "tid": 0,
         "args": {"name": f"lightgbm_tpu rank {r}"}} for r in ranks]
    for s in spans:
        args = dict(s.get("attrs") or {})
        args["trace_id"] = s.get("trace_id")
        args["span_id"] = s.get("span_id")
        if s.get("parent_id") is not None:
            args["parent_id"] = s.get("parent_id")
        events.append({
            "name": s.get("name", "span"), "ph": "X",
            "pid": int(s.get("rank", 0)),
            "tid": int(s.get("thread_id", 0)),
            "ts": float(s.get("start_unix_s", 0.0)) * 1e6,
            "dur": float(s.get("dur_s", 0.0)) * 1e6,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_trace_chrome_trace(path: str, spans: Iterable[Dict]) -> str:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(trace_chrome_trace(spans), fh, sort_keys=True)
    return path


# ---------------------------------------------------------------------------
# JSONL event log + cluster rollup
# ---------------------------------------------------------------------------
def rank_jsonl_path(telemetry_dir: str, rank: int) -> str:
    return os.path.join(telemetry_dir, f"telemetry_rank{int(rank)}.jsonl")


class JsonlEventLog:
    """Append-only one-JSON-object-per-line event sink (one file per rank,
    like the cluster worker logs).  Append mode on purpose: a supervised
    restart reopens the same file and its records accumulate."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(path, "a")

    def emit(self, kind: str, payload: Dict) -> None:
        rec = {"kind": kind}
        rec.update(payload)
        line = json.dumps(rec, default=_json_default)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()


def _json_default(obj):
    try:
        import numpy as np
        if isinstance(obj, np.generic):
            return obj.item()
        if isinstance(obj, np.ndarray):
            return obj.tolist()
    except ImportError:
        pass
    return str(obj)


def rollup_telemetry_dir(telemetry_dir: str,
                         out_path: Optional[str] = None) -> Optional[Dict]:
    """Aggregate every rank's JSONL into one job-level summary dict (and
    write it to ``out_path`` / telemetry_summary.json).

    Iteration records from ALL attempts count (after a supervised restart
    the per-rank files simply grow), so the summary reflects the whole
    job's work, not just the surviving attempt."""
    import glob
    files = sorted(glob.glob(os.path.join(telemetry_dir,
                                          "telemetry_rank*.jsonl")))
    if not files:
        return None
    per_rank: Dict[str, Dict] = {}
    phase_keys = ("iter_s", "grad_s", "grow_s", "hist_s", "split_s",
                  "partition_s", "comm_s", "apply_s", "checkpoint_s")
    for path in files:
        rank_name = os.path.basename(path)[len("telemetry_rank"):-len(".jsonl")]
        iters: List[Dict] = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue       # torn write from a killed worker
                if rec.get("kind") == "iteration":
                    iters.append(rec)
        totals = {k: sum(float(r[k]) for r in iters
                         if isinstance(r.get(k), (int, float)))
                  for k in phase_keys}
        per_rank[rank_name] = {
            "iterations": len(iters),
            "totals": totals,
            "per_iter_s": (totals["iter_s"] / len(iters)) if iters else 0.0,
        }
    n_ranks = len(per_rank)
    total_iters = sum(r["iterations"] for r in per_rank.values())
    summary = {
        "ranks": n_ranks,
        "total_iterations": total_iters,
        "per_rank": per_rank,
        # job totals: straight sums — honest "machine-seconds by phase"
        "totals": {k: sum(r["totals"][k] for r in per_rank.values())
                   for k in phase_keys},
        "max_per_iter_s": max((r["per_iter_s"] for r in per_rank.values()),
                              default=0.0),
    }
    if out_path is None:
        out_path = os.path.join(telemetry_dir, "telemetry_summary.json")
    with open(out_path, "w") as fh:
        json.dump(summary, fh, indent=2)
    summary["path"] = out_path
    return summary
