"""scikit-learn estimator API.

Mirrors the reference python-package/lightgbm/sklearn.py surface
(LGBMModel :349, LGBMRegressor :839, LGBMClassifier :865, LGBMRanker :986)
including the objective/eval-function wrappers (:17,106) that translate
sklearn-style ``func(y_true, y_pred)`` signatures into the native
``(grad, hess)`` / ``(name, value, is_higher_better)`` protocols.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .callback import early_stopping as _early_stopping_cb
from .callback import log_evaluation as _log_evaluation_cb
from .config import resolve_aliases
from .engine import train as _train
from .log import LightGBMError

try:  # graceful degradation when scikit-learn is absent
    from sklearn.base import BaseEstimator as _SKBase
    from sklearn.base import ClassifierMixin as _SKClassifier
    from sklearn.base import RegressorMixin as _SKRegressor
    _SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover
    class _SKBase:  # minimal stand-in
        def get_params(self, deep=True):
            import inspect
            sig = inspect.signature(self.__init__)
            return {k: getattr(self, k) for k in sig.parameters
                    if k not in ("self", "kwargs")}

        def set_params(self, **params):
            for k, v in params.items():
                setattr(self, k, v)
            return self

    class _SKClassifier:
        pass

    class _SKRegressor:
        pass
    _SKLEARN_INSTALLED = False

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]


class _ObjectiveFunctionWrapper:
    """Translate sklearn-style objective ``func(y_true, y_pred[, weight|group])``
    into the native fobj ``(preds, dataset) -> (grad, hess)`` protocol
    (reference sklearn.py:17-105)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            grad, hess = self.func(labels, preds, dataset.get_weight(),
                                   dataset.get_group())
        else:
            raise TypeError(
                f"self-defined objective takes 2-4 arguments, got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Translate sklearn-style metric ``func(y_true, y_pred[, weight|group])``
    into the native feval protocol (reference sklearn.py:106-200)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError(
            f"self-defined eval function takes 2-4 arguments, got {argc}")


class LGBMModel(_SKBase):
    """Base sklearn estimator (reference LGBMModel, sklearn.py:349)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Any] = None,
                 class_weight: Optional[Any] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_score: Dict = {}
        self._best_iteration = -1
        self._objective = objective
        self._n_features = -1
        self._n_classes = -1
        self.fitted_ = False

    # -- param plumbing ---------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep)
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, f"_{key}"):
                setattr(self, f"_{key}", value)
            self._other_params[key] = value
        return self

    def _process_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("objective", None)
        for k in ("class_weight", "importance_type", "n_estimators", "n_jobs"):
            params.pop(k, None)
        # sklearn-name -> native-name translation
        ren = {"subsample": "bagging_fraction",
               "subsample_freq": "bagging_freq",
               "colsample_bytree": "feature_fraction",
               "min_split_gain": "min_gain_to_split",
               "min_child_weight": "min_sum_hessian_in_leaf",
               "min_child_samples": "min_data_in_leaf",
               "reg_alpha": "lambda_l1",
               "reg_lambda": "lambda_l2",
               "subsample_for_bin": "bin_construct_sample_cnt",
               "random_state": "seed"}
        out = {}
        for k, v in params.items():
            out[ren.get(k, k)] = v
        if out.get("seed") is None:
            out.pop("seed", None)
        if out.get("bagging_freq") == 0 and out.get("bagging_fraction", 1.0) < 1.0:
            out["bagging_freq"] = 1
        obj = self.objective
        if callable(obj):
            self._fobj = _ObjectiveFunctionWrapper(obj)
            out["objective"] = "none"
        else:
            self._fobj = None
            if obj is not None:
                out["objective"] = obj
        out["boosting_type"] = self.boosting_type
        return out

    # -- fit --------------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose="warn",
            feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMModel":
        self._objective = self.objective
        params = self._process_params()
        # wire verbosity at the sklearn entry point too (Dataset
        # construction below logs before any Booster applies it)
        from .log import apply_verbosity
        apply_verbosity(params)
        if "objective" not in params and not callable(self.objective):
            params["objective"] = self._default_objective()

        y_proc, sample_weight = self._process_label(y, sample_weight)
        params = self._extend_params_for_label(params)

        evals_result: Dict = {}
        feval = None
        if eval_metric is not None:
            mets = eval_metric if isinstance(eval_metric, list) else [eval_metric]
            str_m = [m for m in mets if isinstance(m, str)]
            fn_m = [_EvalFunctionWrapper(m) for m in mets if callable(m)]
            if str_m:
                params["metric"] = str_m
            if fn_m:
                feval = fn_m

        train_set = Dataset(X, label=y_proc, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            free_raw_data=False)
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):

                def _at(lst, i):
                    return lst[i] if lst is not None and len(lst) > i else None

                if vx is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vy_proc, vw = self._process_label(
                    np.asarray(vy), _at(eval_sample_weight, i), fit=False,
                    class_weight=_at(eval_class_weight, i))
                valid_sets.append(Dataset(
                    vx, label=vy_proc, weight=vw, group=_at(eval_group, i),
                    init_score=_at(eval_init_score, i), reference=train_set,
                    params=params))

        callbacks = list(callbacks or [])
        if early_stopping_rounds is not None and early_stopping_rounds > 0:
            callbacks.append(_early_stopping_cb(early_stopping_rounds,
                                                verbose=bool(verbose)))
        if verbose not in ("warn", False, None) and int(bool(verbose)):
            callbacks.append(_log_evaluation_cb(
                1 if verbose is True else int(verbose)))

        self._Booster = _train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=eval_names,
            feval=feval, fobj=self._fobj, init_model=init_model,
            callbacks=callbacks, evals_result=evals_result)
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self._n_features = train_set.num_feature()
        self._objective = params.get("objective")
        self.fitted_ = True
        return self

    # hooks specialized per estimator ------------------------------------
    def _default_objective(self) -> str:
        return "regression"

    def _process_label(self, y, sample_weight, fit=True,
                       class_weight="__train__"):
        y = np.asarray(y).reshape(-1)
        if class_weight == "__train__":
            # eval sets get their own eval_class_weight (or none), never the
            # training class_weight (reference sklearn.py _get_weight_from_
            # constructed_dataset semantics)
            class_weight = self.class_weight if fit else None
        if class_weight is not None:
            if isinstance(class_weight, str):  # 'balanced'
                from sklearn.utils.class_weight import compute_sample_weight
                w = compute_sample_weight(class_weight, y)
            else:
                w = np.ones(len(y), np.float64)
                for cls, cw in class_weight.items():
                    w[y == cls] = cw
            sample_weight = (w if sample_weight is None
                             else w * np.asarray(sample_weight))
        return y, sample_weight

    def _extend_params_for_label(self, params):
        return params

    # -- predict ----------------------------------------------------------
    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        self._check_fitted()
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=-1 if num_iteration is None else num_iteration,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib, **kwargs)

    def _check_fitted(self):
        if self._Booster is None:
            raise LightGBMError(
                "Estimator not fitted, call fit before exploiting the model.")

    # -- attributes (reference sklearn.py properties) ---------------------
    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def best_score_(self) -> Dict:
        self._check_fitted()
        return self._best_score

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._best_iteration

    @property
    def objective_(self):
        self._check_fitted()
        return self._objective

    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(
            importance_type=self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()


class LGBMRegressor(LGBMModel, _SKRegressor):
    """LightGBM regressor (reference LGBMRegressor, sklearn.py:839)."""

    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(LGBMModel, _SKClassifier):
    """LightGBM classifier (reference LGBMClassifier, sklearn.py:865)."""

    def _default_objective(self) -> str:
        return "binary"

    def _process_label(self, y, sample_weight, fit=True,
                       class_weight="__train__"):
        y = np.asarray(y).reshape(-1)
        if (np.issubdtype(y.dtype, np.number)
                and np.array_equal(self._classes, np.arange(self._n_classes))):
            enc = y.astype(np.float64)
        else:
            enc = np.asarray([self._class_map[v] for v in y], np.float64)
        return super()._process_label(enc, sample_weight, fit, class_weight)

    def _extend_params_for_label(self, params):
        if self._n_classes > 2:
            obj = params.get("objective", "binary")
            if obj in ("binary", None):
                params["objective"] = "multiclass"
            if params.get("objective") in ("multiclass", "multiclassova"):
                params["num_class"] = self._n_classes
        return params

    def _default_objective_multiclass(self):
        return "multiclass"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y).reshape(-1)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        self._class_map = {c: i for i, c in enumerate(self._classes)}
        return super().fit(X, y, **kwargs)

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        result = self.predict_proba(X, raw_score, start_iteration,
                                    num_iteration, pred_leaf, pred_contrib,
                                    **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:  # binary probabilities
            idx = (result > 0.5).astype(int)
        else:
            idx = np.argmax(result, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      **kwargs):
        self._check_fitted()
        result = self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=-1 if num_iteration is None else num_iteration,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes <= 2 and np.ndim(result) == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """LightGBM ranker (reference LGBMRanker, sklearn.py:986)."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), early_stopping_rounds=None,
            verbose="warn", feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not None")
        self._eval_at = list(eval_at)
        extra = {"eval_at": list(eval_at)}
        self._other_params.update(extra)
        setattr(self, "eval_at", list(eval_at))
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            group=group, eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_group=eval_group,
            eval_metric=eval_metric,
            early_stopping_rounds=early_stopping_rounds, verbose=verbose,
            feature_name=feature_name, categorical_feature=categorical_feature,
            callbacks=callbacks, init_model=init_model)
