"""LightGBM-compatible binding-level API: Dataset and Booster.

Mirrors python-package/lightgbm/basic.py (Dataset :1125, Booster :2465) so a
reference user can switch imports.  There is no C-API indirection here — the
"native" layer is the jitted device program — but the semantics match: lazy
Dataset construction with binning params frozen at construct time, validation
sets aligned to their reference Dataset's bin mappers (basic.py:1232
_init_from_ref_dataset), Booster.update with optional custom fobj.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .config import Config
from .dataset import Metadata, TrainDataset, ValidDataset
from .log import LightGBMError, log_info, log_warning, set_verbosity
from .tree import Tree

__all__ = ["Dataset", "Booster", "Sequence"]


class Sequence:
    """Generic data access interface for chunked out-of-core ingestion
    (reference basic.py:608-672 Sequence ABC)."""
    batch_size = 4096

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


def _to_2d_numpy(data) -> np.ndarray:
    if isinstance(data, np.ndarray):
        arr = data
    elif hasattr(data, "toarray"):          # scipy sparse
        arr = data.toarray()
    elif type(data).__name__ == "DataFrame":  # pandas without hard dep
        arr = data.to_numpy()
    elif isinstance(data, Sequence):
        arr = np.concatenate([np.atleast_2d(np.asarray(data[i]))
                              for i in range(len(data))], axis=0)
    elif isinstance(data, list) and data and isinstance(data[0], Sequence):
        arr = np.concatenate([_to_2d_numpy(s) for s in data], axis=0)
    else:
        arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return np.ascontiguousarray(arr, dtype=np.float64)


def _pandas_categorical(df):
    """Extract categorical columns + integer-code them (reference
    basic.py:518-606 pandas handling)."""
    cat_cols = [i for i, dt in enumerate(df.dtypes)
                if str(dt) == "category"]
    if not cat_cols:
        return df.to_numpy(dtype=np.float64, na_value=np.nan), []
    import pandas as pd
    out = df.copy()
    for i in cat_cols:
        col = out.columns[i]
        out[col] = out[col].cat.codes.replace(-1, np.nan)
    return out.to_numpy(dtype=np.float64, na_value=np.nan), cat_cols


class Dataset:
    """Lazy-constructed dataset (reference lightgbm.Dataset, basic.py:1125)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name="auto", categorical_feature="auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._handle = None          # TrainDataset or ValidDataset
        self._used_indices = None
        # user-supplied names win; DataFrame columns fill in during
        # construct() when feature_name stays "auto" (reference
        # _set_init_from_params feature_name handling)
        self._feature_names: Optional[List[str]] = (
            [str(n) for n in feature_name]
            if isinstance(feature_name, (list, tuple)) else None)
        self._pandas_cats: List[int] = []

    # ------------------------------------------------------------------
    def construct(self) -> "Dataset":
        if self._handle is None:
            self._construct_impl()
            self._sync_feature_names()
        return self

    def _construct_impl(self) -> "Dataset":
        if self._handle is not None:
            return self
        if self.reference is not None:
            self.reference.construct()
        data = self.data
        if data is None:
            raise LightGBMError("cannot construct Dataset: raw data was freed")
        cfg0 = Config(self.params)
        rank_sharded = (self.reference is None and self._used_indices is None
                        and cfg0.num_machines > 1
                        and cfg0.tree_learner in ("data", "voting")
                        and (isinstance(data, str) or cfg0.pre_partition))
        if rank_sharded:
            # distributed loading: each rank materializes only its row shard
            # (reference dataset_loader.cpp:182 rank-aware load + :1044-1127
            # distributed bin-finding).  pre_partition=true means `data` is
            # already this rank's share (its own file / its own arrays);
            # otherwise ranks round-robin the shared file's rows.
            if self.group is not None:
                raise LightGBMError(
                    "query/group data requires pre-partitioned loading by "
                    "query; not supported with rank-sharded ingestion")
            from .parallel.mesh import (comm_rank, comm_size,
                                        maybe_init_distributed)
            maybe_init_distributed(cfg0)
            if isinstance(data, str):
                from .io.parser import load_side_file
                side_w = load_side_file(data + ".weight")
                if load_side_file(data + ".query") is not None:
                    raise LightGBMError(
                        "a .query side file requires query-aligned "
                        "partitioning; not supported with rank-sharded "
                        "ingestion")
                if cfg0.pre_partition:
                    # the file (and its side files) already hold only this
                    # rank's rows
                    from .io.parser import load_svmlight_or_csv
                    X_local, y_local = load_svmlight_or_csv(data)
                    if side_w is not None and self.weight is None:
                        self.weight = side_w
                else:
                    from .io.parser import load_rank_shard
                    rk, nm = comm_rank(), comm_size()
                    X_local, y_local = load_rank_shard(data, rk, nm)
                    if side_w is not None and self.weight is None:
                        # slice the global side file the same round-robin way
                        self.weight = side_w[rk::nm]
                if self.label is not None:
                    raise LightGBMError(
                        "rank-sharded file loading takes labels from the "
                        "file's label column")
            else:
                if hasattr(data, "tocsc") and not isinstance(data, np.ndarray):
                    X_local = data      # from_rank_shard bins sparse shards
                else:
                    X_local = _to_2d_numpy(data)
                y_local = np.asarray(self.label, np.float32)
            cats = self._resolve_categoricals(X_local.shape[1])
            self._handle = TrainDataset.from_rank_shard(
                X_local, y_local, cfg0, categorical_features=cats,
                weight_local=self.weight,
                init_score_local=self.init_score)
            if self.free_raw_data:
                self.data = None
            return self
        if isinstance(data, str) and self._used_indices is None:
            # side files (reference DatasetLoader::LoadFromFile picks up
            # <data>.weight and <data>.query automatically); applies to
            # every file-loading branch below, but not to subsets (a full
            # -file group cannot align with sliced rows)
            from .io.parser import load_side_file
            if self.weight is None:
                self.weight = load_side_file(data + ".weight")
            if self.group is None:
                self.group = load_side_file(data + ".query")
        if (isinstance(data, str) and cfg0.two_round
                and self.reference is None and self._used_indices is None):
            # two_round (reference config.h two_round / TwoPassLoading):
            # stream the file twice, binning chunks straight into the
            # packed matrix — the raw float64 matrix never materializes
            self._handle = TrainDataset.from_text_two_round(
                data, cfg0,
                categorical_features=self._resolve_categoricals(0),
                weight=self.weight, group=self.group,
                init_score=self.init_score,
                label_override=self.label)
            if self.free_raw_data:
                self.data = None
            return self
        if isinstance(data, str):
            from .io.parser import load_svmlight_or_csv
            arr, label = load_svmlight_or_csv(data)
            if self.label is None:
                self.label = label
        elif type(data).__name__ == "DataFrame":
            if self._feature_names is None:
                self._feature_names = [str(c) for c in data.columns]
            arr, self._pandas_cats = _pandas_categorical(data)
        elif (self.reference is None and self._used_indices is None
              and (isinstance(data, Sequence)
                   or (isinstance(data, list) and data
                       and isinstance(data[0], Sequence)))):
            # out-of-core path: two-round streaming construction, the raw
            # matrix is never materialized (reference Sequence +
            # two_round semantics, basic.py:608, utils/pipeline_reader.h)
            seqs = [data] if isinstance(data, Sequence) else list(data)
            n = int(sum(len(s) for s in seqs))
            meta = self._make_metadata(n)
            cfg = Config(self.params)
            cats = self._resolve_categoricals(0)
            self._handle = TrainDataset.from_sequences(
                seqs, meta, cfg, categorical_features=cats)
            if self.free_raw_data:
                self.data = None
            return self
        elif (hasattr(data, "tocsc") and not isinstance(data, np.ndarray)
              and self._used_indices is None):
            # scipy sparse: bin columns from the nonzeros; the dense float64
            # matrix is never materialized (reference CSR/CSC ingestion,
            # c_api.cpp LGBM_DatasetCreateFromCSR)
            meta = self._make_metadata(data.shape[0])
            cfg = Config(self.params)
            cats = self._resolve_categoricals(data.shape[1])
            if self.reference is not None:
                self._handle = self.reference._handle.create_valid(data, meta)
            else:
                self._handle = TrainDataset.from_sparse(
                    data, meta, cfg, categorical_features=cats)
            if self.free_raw_data:
                self.data = None
            return self
        else:
            arr = _to_2d_numpy(data)

        if self._used_indices is not None:
            arr = arr[self._used_indices]

        label = self._slice(self.label)
        if label is None:
            label = np.zeros(arr.shape[0], np.float32)
        meta = Metadata(np.asarray(label),
                        self._slice(self.weight),
                        np.asarray(self.group) if self.group is not None else None,
                        self._slice(self.init_score))

        cfg = Config(self.params)
        cats = self._resolve_categoricals(arr.shape[1])
        if self.reference is not None:
            if self.params.get("reference_as_train"):
                # continued-training alignment (ISSUE 10): a TRAIN dataset
                # binned with the reference's frozen mappers AND frozen EFB
                # bundles — O(rows) setup, bit-identical to extending the
                # reference with the same rows (dataset.from_reference)
                self._handle = TrainDataset.from_reference(
                    self.reference._handle, arr, meta)
            else:
                self._handle = self.reference._handle.create_valid(arr, meta)
        else:
            self._handle = TrainDataset(arr, meta, cfg,
                                        categorical_features=cats)
        if self.free_raw_data:
            self.data = None
        return self

    def _sync_feature_names(self) -> None:
        """Attach user/DataFrame names to the live handle so the save path
        reads them (reference Dataset::set_feature_name).  Called at the
        end of construct() and again on later renames.  The model text
        joins names with spaces, so whitespace is replaced (the reference
        python package sanitizes the same way) and a length mismatch is a
        hard error (reference: 'Length of feature_name error')."""
        if self._handle is None or not self._feature_names:
            return
        nf = getattr(self._handle, "num_total_features", None)
        if nf is None:            # valid datasets take the train set's names
            return
        if len(self._feature_names) != nf:
            raise LightGBMError(
                f"Length of feature_name ({len(self._feature_names)}) does "
                f"not match the number of features ({nf})")
        cleaned = []
        for n in self._feature_names:
            s = "_".join(str(n).split())
            if s != str(n):
                log_warning(f"feature name {n!r} contains whitespace; "
                            f"saved as {s!r} (model text is space-joined)")
            cleaned.append(s)
        self._feature_names = cleaned
        self._handle.user_feature_names = cleaned

    def _make_metadata(self, n: int) -> Metadata:
        """Metadata from the user-supplied label/weight/group/init_score
        (zero labels when none given), for the streaming/sparse paths."""
        label = self.label if self.label is not None else np.zeros(
            n, np.float32)
        return Metadata(np.asarray(label),
                        None if self.weight is None
                        else np.asarray(self.weight),
                        np.asarray(self.group)
                        if self.group is not None else None,
                        None if self.init_score is None
                        else np.asarray(self.init_score))

    def _slice(self, x):
        if x is None:
            return None
        x = np.asarray(x)
        if self._used_indices is not None and len(x) != len(self._used_indices):
            x = x[self._used_indices]
        return x

    def _resolve_categoricals(self, num_features: int) -> List[int]:
        cf = self.categorical_feature
        if cf == "auto" or cf is None:
            return list(self._pandas_cats)
        out = []
        for c in cf:
            if isinstance(c, str):
                if self._feature_names and c in self._feature_names:
                    out.append(self._feature_names.index(c))
            else:
                out.append(int(c))
        return sorted(set(out) | set(self._pandas_cats))

    @classmethod
    def _from_handle(cls, handle, params=None) -> "Dataset":
        """Wrap an already-constructed TrainDataset handle (the continuous
        trainer's persistent incremental store) so ``engine.train`` can
        consume it without re-binning or re-concatenating raw data.
        ``construct()`` is a no-op on the wrapper."""
        ds = cls.__new__(cls)
        ds.data = None
        ds.label = None
        ds.reference = None
        ds.weight = None
        ds.group = None
        ds.init_score = None
        ds.feature_name = "auto"
        ds.categorical_feature = "auto"
        ds.params = dict(params or {})
        ds.free_raw_data = False
        ds._handle = handle
        ds._used_indices = None
        ds._feature_names = None
        ds._pandas_cats = []
        return ds

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score, params=params)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        """Row subset sharing binning params (reference Dataset.subset;
        CopySubrow dataset.h:416).  Used by cv()."""
        ds = Dataset(self.data, label=self.label, weight=self.weight,
                     group=self.group, init_score=self.init_score,
                     feature_name=self.feature_name,
                     categorical_feature=self.categorical_feature,
                     params=params or self.params, free_raw_data=False)
        ds._used_indices = np.asarray(used_indices)
        ds.reference = self.reference
        return ds

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Merge another Dataset's features into this one column-wise
        (reference Dataset::AddFeaturesFrom, dataset.cpp:754 /
        LGBM_DatasetAddFeaturesFrom).  Both datasets keep their own bin
        mappers; the other's feature indices shift by this dataset's
        feature count.  Raw data (linear-tree support) is not carried."""
        self.construct()
        other.construct()
        a, b = self._handle, other._handle
        if not isinstance(a, TrainDataset) or not isinstance(b, TrainDataset):
            raise LightGBMError("add_features_from requires two constructed "
                                "train Datasets")
        if a.num_data != b.num_data:
            raise LightGBMError(
                f"cannot add features: row counts differ "
                f"({a.num_data} vs {b.num_data})")
        if getattr(a, "rank_local", False) or getattr(b, "rank_local", False):
            raise LightGBMError("add_features_from is not supported for "
                                "rank-sharded datasets")
        mappers = list(a.all_bin_mappers) + list(b.all_bin_mappers)
        bins = np.concatenate([np.asarray(a.bins), np.asarray(b.bins)],
                              axis=1)
        merged = TrainDataset.__new__(TrainDataset)
        merged._init_from_binned(
            bins, mappers, a.num_total_features + b.num_total_features,
            a.metadata, a.config)
        self._handle = merged
        if self._feature_names and other._feature_names:
            self._feature_names = (list(self._feature_names)
                                   + list(other._feature_names))
        else:
            self._feature_names = None
        return self

    def set_label(self, label):
        self.label = label
        if self._handle is not None:
            self._handle.metadata.label = np.asarray(label, np.float32)
            h = self._handle
            if hasattr(h, "label"):
                import jax.numpy as jnp
                h.label = jnp.asarray(h.metadata.label)
        return self

    def _refresh_metadata(self) -> None:
        """Propagate post-construct field updates into the live handle
        (reference Metadata::SetWeights/SetQuery mutate in place)."""
        h = self._handle
        if h is None:
            return
        md = h.metadata
        new = Metadata(md.label, self.weight,
                       np.asarray(self.group) if self.group is not None
                       else None,
                       self.init_score)
        h.metadata = new
        import jax.numpy as jnp
        if hasattr(h, "weight"):
            h.weight = (jnp.asarray(new.weight)
                        if new.weight is not None else None)
        if hasattr(h, "query_ids"):
            h.query_ids = (jnp.asarray(new.query_ids)
                           if new.query_ids is not None else None)

    def set_weight(self, weight):
        self.weight = weight
        self._refresh_metadata()
        return self

    def set_group(self, group):
        self.group = group
        self._refresh_metadata()
        return self

    def set_init_score(self, init_score):
        self.init_score = init_score
        self._refresh_metadata()
        return self

    def get_label(self):
        if self._handle is not None:
            return np.asarray(self._handle.metadata.label)
        return np.asarray(self.label) if self.label is not None else None

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    # -- reference Dataset conveniences ---------------------------------
    def get_data(self):
        """reference Dataset.get_data: the raw data if it was kept
        (free_raw_data=False), else an error like the reference."""
        if self.data is None:
            raise LightGBMError("Cannot get data: set free_raw_data=False "
                                "when constructing the Dataset")
        return self.data

    def get_init_score(self):
        return self.init_score

    def get_feature_name(self) -> List[str]:
        return self.get_feature_names()

    def set_feature_name(self, feature_name) -> "Dataset":
        """reference Dataset.set_feature_name."""
        self._feature_names = [str(n) for n in feature_name]
        self._sync_feature_names()
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """reference Dataset.set_categorical_feature (before construct)."""
        if self._handle is not None and \
                categorical_feature != self.categorical_feature:
            raise LightGBMError(
                "Cannot change categorical_feature after the Dataset was "
                "constructed; create a new Dataset instead")
        self.categorical_feature = categorical_feature
        return self

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """reference Dataset.set_reference (before construct)."""
        if self._handle is not None and reference is not self.reference:
            raise LightGBMError(
                "Cannot set reference after the Dataset was constructed; "
                "create a new Dataset instead")
        self.reference = reference
        return self

    def get_ref_chain(self, ref_limit: int = 100):
        """reference Dataset.get_ref_chain: this dataset and its ancestry."""
        chain, node = [], self
        while node is not None and len(chain) < ref_limit:
            chain.append(node)
            node = node.reference
        return set(chain)

    def get_params(self) -> Dict[str, Any]:
        return dict(self.params)

    def set_field(self, field_name: str, data) -> "Dataset":
        """reference Dataset.set_field dispatch."""
        setter = {"label": self.set_label, "weight": self.set_weight,
                  "group": self.set_group,
                  "init_score": self.set_init_score}.get(field_name)
        if setter is None:
            raise LightGBMError(f"unknown field {field_name!r}")
        setter(data)
        return self

    def get_field(self, field_name: str):
        """reference Dataset.get_field dispatch."""
        getter = {"label": self.get_label, "weight": self.get_weight,
                  "group": self.get_group,
                  "init_score": self.get_init_score}.get(field_name)
        if getter is None:
            raise LightGBMError(f"unknown field {field_name!r}")
        return getter()

    def num_data(self) -> int:
        self.construct()
        return self._handle.num_data

    def num_feature(self) -> int:
        self.construct()
        h = self._handle
        return (h.num_total_features if isinstance(h, TrainDataset)
                else h.train.num_total_features)

    def get_feature_names(self) -> List[str]:
        if self._feature_names:
            return self._feature_names
        return [f"Column_{i}" for i in range(self.num_feature())]

    def save_binary(self, filename: str) -> "Dataset":
        """Binned-dataset cache (reference Dataset::SaveBinaryFile)."""
        self.construct()
        from .io.binary_cache import save_dataset
        save_dataset(self._handle, filename)
        return self

    @staticmethod
    def from_binary(filename: str, params=None) -> "Dataset":
        from .io.binary_cache import load_dataset
        handle = load_dataset(filename, Config(params or {}))
        ds = Dataset(None, free_raw_data=False)
        ds._handle = handle
        return ds


class _RWLock:
    """Reader-writer lock guarding Booster mutation vs concurrent predict
    (reference: yamc shared-mutex around Booster train/predict,
    src/c_api.cpp:106,831).  Writer-exclusive, multiple readers."""

    def __init__(self):
        import threading
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    def acquire_read(self):
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            while self._writer or self._readers:
                self._cond.wait()
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    from contextlib import contextmanager

    @contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()

    def __getstate__(self):
        return {}          # locks don't pickle; a fresh one is equivalent

    def __setstate__(self, state):
        self.__init__()


class Booster:
    """Training/prediction handle (reference lightgbm.Booster, basic.py:2465)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = dict(params or {})
        self._gbdt = None
        self._lock = _RWLock()
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._train_set = train_set
        self._loaded_trees: Optional[List[Tree]] = None
        self._loaded_meta: Dict[str, str] = {}
        self._valid_names: List[str] = []
        self._valid_sets_refs: List[Dataset] = []
        # device-resident StackedTrees per (start, n_trees): stacking packs
        # T trees into padded parallel arrays, which is pure overhead to
        # repeat per predict call; any model mutation bumps the version and
        # drops the cache (see _invalidate_stacked).  LRU-bounded: looping
        # over num_iteration values would otherwise pin O(N^2) tree copies
        # on device
        self._stacked_cache: "OrderedDict" = OrderedDict()
        self._stacked_cache_cap = 8
        # cascade tail bounds (ops.predict.tree_tail_bounds) for the FULL
        # model, invalidated with the stacked cache under _model_version —
        # the serving predictor snapshots it next to stacked_trees()
        self._tail_bounds_cache = None
        # dedicated mutex for the cache dict itself: stacked_trees runs
        # under the shared READ lock (predict) or no lock (to_compiled),
        # so LRU mutation must not race concurrent readers or a writer's
        # _invalidate_stacked clear
        import threading
        self._stacked_lock = threading.Lock()
        self._model_version = 0

        if model_file is not None:
            with open(model_file) as fh:
                model_str = fh.read()
        if model_str is not None:
            self._load_from_string(model_str)
            return
        if train_set is None:
            raise LightGBMError("Booster requires train_set or model file")
        cfg = Config(self.params)
        set_verbosity(cfg.verbosity)
        train_set.params = dict(train_set.params or self.params)
        train_set.construct()
        from .objectives import create_objective
        from .boosting import create_boosting
        self._config = cfg
        self._objective = create_objective(cfg)
        self._gbdt = create_boosting(cfg, train_set._handle, self._objective)

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.reference = data.reference or self._train_set
        data.params = dict(data.params or self.params)
        data.construct()
        self._gbdt.add_valid(data._handle, name)
        self._valid_names.append(name)
        self._valid_sets_refs.append(data)
        return self

    def update(self, train_set=None, fobj=None) -> bool:
        """One boosting iteration; returns True if no further splits possible
        (reference LGBM_BoosterUpdateOneIter / ...Custom, c_api.cpp:1677,1698;
        write-locked like the reference Booster's shared-mutex)."""
        with self._lock.write():
            self._invalidate_stacked()
            if fobj is not None:
                score = self._raw_train_score()
                grad, hess = fobj(score, self._train_set)
                return self._gbdt.train_one_iter(grad, hess)
            return self._gbdt.train_one_iter()

    def supports_fused_blocks(self) -> bool:
        """True when this booster can run multiple rounds as one compiled
        program (GBDT.train_block; serial learner, telemetry off, no valid
        sets, built-in objective)."""
        return self._gbdt is not None and self._gbdt._can_fuse()

    def update_block(self, k: int):
        """Run up to ``k`` boosting rounds as one fused program (falls back
        to per-round steps when the config can't fuse); returns
        (rounds_run, stop) — the multi-round counterpart of update()."""
        with self._lock.write():
            self._invalidate_stacked()
            return self._gbdt.train_block(k)

    def _raw_train_score(self):
        score = np.asarray(self._gbdt.train_score)
        if self._gbdt.num_class == 1:
            return score[0]
        return score.T  # sklearn convention [N, K]

    def rollback_one_iter(self) -> "Booster":
        with self._lock.write():
            self._invalidate_stacked()
            self._gbdt.rollback_one_iter()
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Re-resolve tunable parameters mid-training (reference
        Booster.reset_parameter -> LGBM_BoosterResetParameter,
        c_api.cpp:1660 GBDT::ResetConfig).  Structural dataset params
        (max_bin etc.) are frozen at construct time, like the reference."""
        with self._lock.write():
            self.params.update(params)
            cfg = Config(self.params)
            self._config = cfg
            if self._gbdt is not None:
                self._gbdt.reset_config(cfg)
        return self

    def current_iteration(self) -> int:
        return self._gbdt.current_iteration()

    def num_trees(self) -> int:
        return self._gbdt.num_trees if self._gbdt else len(self._loaded_trees)

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_class if self._gbdt else int(
            self._loaded_meta.get("num_tree_per_iteration", 1))

    def telemetry_stats(self, start: int = 0) -> Optional[List[Dict]]:
        """Per-iteration training stats (telemetry/training.py records) or
        None when the booster trained with ``telemetry=off``.  ``start``
        skips already-consumed records so streaming consumers (the
        record_telemetry callback) stay O(new), not O(all), per call."""
        tele = getattr(self._gbdt, "telemetry", None) if self._gbdt else None
        if tele is None:
            return None
        return [dict(r) for r in tele.records[start:]]

    def telemetry_summary(self) -> Optional[Dict]:
        """Aggregated view of telemetry_stats(), or None when off."""
        tele = getattr(self._gbdt, "telemetry", None) if self._gbdt else None
        return tele.summary() if tele is not None else None

    def eval_valid(self, feval=None) -> List[tuple]:
        return [t for name in self._valid_names
                for t in self._eval_set(name, feval)]

    def eval_train(self, feval=None) -> List[tuple]:
        return self._eval_set("training", feval)

    def _eval_set(self, name, feval=None) -> List[tuple]:
        g = self._gbdt
        results = []
        if name == "training":
            data_meta = g.train_data.metadata
            score = g.train_score
            if score.shape[-1] != g.train_data.num_data:
                # row-bucket padding: metrics see the real rows only
                score = score[:, :g.train_data.num_data]
        else:
            i = self._valid_names.index(name)
            data_meta = g.valid_sets[i].metadata
            score = g.valid_scores[i]
        raw = score[0] if g.num_class == 1 else score
        for m in g.train_metrics:
            for mname, val, hib in m.eval(raw, data_meta.label, data_meta.weight,
                                          g.objective, data_meta.query_boundaries):
                results.append((name, mname, val, hib))
        if feval is not None:
            ds = (self._train_set if name == "training" else None)
            raw_np = np.asarray(raw) if g.num_class == 1 else np.asarray(raw).T
            for r in _call_feval(feval, raw_np, data_meta):
                results.append((name, r[0], r[1], r[2]))
        return results

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        if isinstance(data, str):
            from .io.parser import load_svmlight_or_csv
            data, _ = load_svmlight_or_csv(data)
        elif type(data).__name__ == "DataFrame":
            data, _ = _pandas_categorical(data)
        elif hasattr(data, "tocsr") and not isinstance(data, np.ndarray):
            # scipy sparse: tree traversal needs raw values, so densify in
            # bounded chunks instead of all at once (reference
            # LGBM_BoosterPredictForCSR reconstructs rows the same way)
            csr = data.tocsr()
            if csr.shape[0] == 0:
                return self.predict(np.zeros(csr.shape), start_iteration,
                                    num_iteration, raw_score, pred_leaf,
                                    pred_contrib, **kwargs)
            step = 1 << 16
            outs = [self.predict(csr[lo:lo + step].toarray(),
                                 start_iteration, num_iteration, raw_score,
                                 pred_leaf, pred_contrib, **kwargs)
                    for lo in range(0, csr.shape[0], step)]
            return np.concatenate(outs, axis=0)
        else:
            data = _to_2d_numpy(data)
        if num_iteration is None:
            num_iteration = -1
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        with self._lock.read():
            if self._gbdt is not None:
                if pred_leaf:
                    return self._gbdt.predict_leaf_index(
                        data, start_iteration, num_iteration,
                        stacked=self.stacked_trees(start_iteration,
                                                   num_iteration))
                if pred_contrib:
                    from .contrib import predict_contrib
                    return predict_contrib(self._trees_for_range(
                        start_iteration, num_iteration), data,
                        self.num_model_per_iteration())
                return self._gbdt.predict(data, raw_score, start_iteration,
                                          num_iteration)
            return self._predict_loaded(data, start_iteration, num_iteration,
                                        raw_score, pred_leaf, pred_contrib)

    def _invalidate_stacked(self) -> None:
        """Drop cached StackedTrees after any model mutation (train step,
        rollback, shuffle, reload, refit): the packed device arrays would
        silently keep predicting the old trees otherwise."""
        with self._stacked_lock:
            self._model_version += 1
            self._stacked_cache.clear()
            self._tail_bounds_cache = None

    def stacked_trees(self, start_iteration: int = 0,
                      num_iteration: int = -1):
        """Cached device-resident StackedTrees for a tree range.

        Stacking (ops/predict.py stack_trees) packs the range's trees into
        padded parallel arrays once; repeated predict calls reuse the
        arrays instead of re-packing per call.  The cache is invalidated
        whenever trees are added, rolled back, reordered, reloaded, or
        refit (_invalidate_stacked)."""
        from .ops.predict import stack_trees
        with self._stacked_lock:
            version = self._model_version
        trees = self._trees_for_range(start_iteration, num_iteration)
        if not trees:
            return None
        key = (start_iteration, len(trees))
        with self._stacked_lock:
            if version == self._model_version:
                hit = self._stacked_cache.get(key)
                if hit is not None:
                    self._stacked_cache.move_to_end(key)
                    return hit
        # stack outside the mutex (it's the expensive device packing); a
        # rare duplicate stacking on a concurrent miss is harmless
        hit = stack_trees(trees)
        with self._stacked_lock:
            if version != self._model_version:
                # the model mutated while we were stacking: hand the caller
                # its (consistent-at-read-time) snapshot but do NOT cache
                # it — mutations that preserve tree count (shuffle, refit)
                # would leave the stale pack under a colliding key forever
                return hit
            cur = self._stacked_cache.get(key)
            if cur is not None:
                self._stacked_cache.move_to_end(key)
                return cur
            self._stacked_cache[key] = hit
            while len(self._stacked_cache) > self._stacked_cache_cap:
                self._stacked_cache.popitem(last=False)
        return hit

    def tail_bounds(self) -> "np.ndarray":
        """Cached per-class cascade tail bounds for the full model
        (ops.predict.tree_tail_bounds): row t bounds |sum of leaf values
        of iterations t..end| per class, so ``tail[K] - tail[e]`` is the
        exact uncertainty half-width of a K-iteration prefix score
        against the [K, e) completion.  Invalidated with the stacked
        cache under _model_version, same contract as stacked_trees()."""
        from .ops.predict import tree_tail_bounds
        with self._stacked_lock:
            version = self._model_version
            hit = self._tail_bounds_cache
        if hit is not None:
            return hit
        out = tree_tail_bounds(self._trees_for_range(0, -1),
                               self.num_model_per_iteration())
        with self._stacked_lock:
            if version == self._model_version:
                self._tail_bounds_cache = out
        return out

    def to_compiled(self, buckets=None, dtype=None, **kwargs):
        """Build a serving-grade CompiledPredictor from this model.

        The predictor keeps the stacked trees on device and jit-caches one
        program per (row bucket, feature count, iteration range, output
        kind), so steady-state traffic causes zero recompiles after warmup
        (see lightgbm_tpu/serving/compiled.py)."""
        from .serving.compiled import CompiledPredictor
        return CompiledPredictor(self, buckets=buckets, dtype=dtype, **kwargs)

    def _trees_for_range(self, start_iteration, num_iteration):
        k = self.num_model_per_iteration()
        models = self._gbdt.models if self._gbdt else self._loaded_trees
        n_iter = len(models) // k
        end = n_iter if num_iteration < 0 else min(
            start_iteration + num_iteration, n_iter)
        return models[start_iteration * k: end * k]

    def _predict_loaded(self, data, start_iteration, num_iteration, raw_score,
                        pred_leaf, pred_contrib):
        trees = self._trees_for_range(start_iteration, num_iteration)
        k = int(self._loaded_meta.get("num_tree_per_iteration", 1))
        n = data.shape[0]
        if pred_leaf:
            return np.stack([t.predict_leaf_index(data) for t in trees], axis=1)
        if pred_contrib:
            from .contrib import predict_contrib
            return predict_contrib(trees, data, k)
        if k == 1:
            out = np.zeros(n)
            for t in trees:
                out += t.predict(data)
        else:
            out = np.zeros((n, k))
            for i, t in enumerate(trees):
                out[:, i % k] += t.predict(data)
        if self._loaded_meta.get("average_output"):
            out /= max(len(trees) // k, 1)
        if raw_score:
            return out
        return self._convert_loaded_output(out)

    def _convert_loaded_output(self, raw):
        from .objectives import output_transform
        obj = self._loaded_meta.get("objective", "")
        # loaded-model layout is [N, K] -> class_axis=1; the serving path
        # (serving/compiled.py) shares this exact transform on [K, N]
        return output_transform(obj, xp=np, class_axis=1)(raw)

    # ------------------------------------------------------------------
    # -- reference Booster conveniences ---------------------------------
    def attr(self, key: str):
        """reference Booster.attr: stored model attribute or None."""
        return getattr(self, "_attr", {}).get(key)

    def set_attr(self, **kwargs) -> "Booster":
        """reference Booster.set_attr: set (str) or delete (None) model
        attributes."""
        store = getattr(self, "_attr", None)
        if store is None:
            store = self._attr = {}
        for k, v in kwargs.items():
            if v is None:
                store.pop(k, None)
            else:
                store[k] = str(v)
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """reference Booster.set_train_data_name."""
        self._train_data_name = name
        return self

    def free_dataset(self) -> "Booster":
        """reference Booster.free_dataset: release train/valid data memory
        (prediction keeps working through the retained bin mappers; no
        further training)."""
        self._train_set = None
        if self._gbdt is not None:
            self._gbdt.free_dataset()
        return self

    def free_network(self) -> "Booster":
        """reference Booster.free_network (LGBM_NetworkFree)."""
        from .parallel.mesh import shutdown_distributed
        shutdown_distributed()
        return self

    def model_from_string(self, model_str: str) -> "Booster":
        """reference Booster.model_from_string: replace this booster's
        model with one parsed from text."""
        with self._lock.write():
            self._invalidate_stacked()
            self._gbdt = None
            self._load_from_string(model_str)
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """reference Booster.get_leaf_output (LGBM_BoosterGetLeafValue;
        errors on out-of-range leaf ids rather than returning padding)."""
        models = self._gbdt.models if self._gbdt else self._loaded_trees
        tree = models[tree_id]
        if not 0 <= leaf_id < tree.num_leaves:
            raise LightGBMError(
                f"leaf_id {leaf_id} out of range for tree {tree_id} "
                f"({tree.num_leaves} leaves)")
        return float(tree.leaf_value[leaf_id])

    def lower_bound(self) -> float:
        """reference Booster.lower_bound: smallest possible raw score
        (sum over trees of each tree's minimum leaf value)."""
        models = self._gbdt.models if self._gbdt else self._loaded_trees
        return float(sum(float(np.min(t.leaf_value[:t.num_leaves]))
                         for t in models))

    def upper_bound(self) -> float:
        """reference Booster.upper_bound."""
        models = self._gbdt.models if self._gbdt else self._loaded_trees
        return float(sum(float(np.max(t.leaf_value[:t.num_leaves]))
                         for t in models))

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """reference Booster.shuffle_models (LGBM_BoosterShuffleModels):
        randomly permute the tree order inside [start, end) iterations —
        used to decorrelate prediction early-stopping."""
        with self._lock.write():
            self._invalidate_stacked()
            models = self._gbdt.models if self._gbdt else self._loaded_trees
            k = self.num_model_per_iteration()
            n_iter = len(models) // k
            end = n_iter if end_iteration < 0 else min(end_iteration, n_iter)
            idx = np.arange(start_iteration, end)
            np.random.shuffle(idx)
            blocks = [models[i * k:(i + 1) * k] for i in range(n_iter)]
            reordered = (blocks[:start_iteration]
                         + [blocks[i] for i in idx] + blocks[end:])
            flat = [t for b in reordered for t in b]
            if self._gbdt:
                self._gbdt.models = flat
            else:
                self._loaded_trees = flat
        return self

    def get_split_value_histogram(self, feature, bins=None):
        """reference Booster.get_split_value_histogram: histogram of the
        thresholds this model splits `feature` at (default bin count =
        number of distinct thresholds, like the reference)."""
        from .plotting import split_value_counts
        values = split_value_counts(self, feature)
        if bins is None:
            bins = max(len(np.unique(values)), 1)
        return np.histogram(values, bins=bins)

    def eval(self, data, name: str, feval=None):
        """reference Booster.eval: evaluate the model's metrics on a
        Dataset.  Matches tracked datasets by IDENTITY (the reference
        compares `data is train_set` / the valid list); an unseen dataset
        is registered as a new valid set under `name`."""
        if self._gbdt is None:
            raise LightGBMError(
                "eval requires a trained Booster (predictor boosters "
                "loaded from a model file have no metrics state)")
        if data is self._train_set:
            return self.eval_train(feval)
        for i, vn in enumerate(self._valid_names):
            if data is self._valid_sets_refs[i]:
                return self._eval_set(vn, feval)
        if name == "training" or name in self._valid_names:
            raise LightGBMError(
                f"name {name!r} already refers to a different dataset; "
                "pick a fresh name for a new eval set")
        self.add_valid(data, name)
        return self._eval_set(name, feval)

    def trees_to_dataframe(self):
        """Flatten the model into a pandas DataFrame, one row per node/leaf
        (reference Booster.trees_to_dataframe, basic.py:3572): columns
        tree_index, node_depth, node_index, left/right_child, parent_index,
        split_feature, split_gain, threshold, decision_type, missing_type,
        value, weight, count."""
        import pandas as pd
        names = self.feature_name()
        rows = []

        def walk(tree_index, node, parent, depth):
            if "leaf_index" in node:
                rows.append({
                    "tree_index": tree_index, "node_depth": depth,
                    "node_index": f"{tree_index}-L{node['leaf_index']}",
                    "left_child": None, "right_child": None,
                    "parent_index": parent, "split_feature": None,
                    "split_gain": None, "threshold": None,
                    "decision_type": None, "missing_type": None,
                    "value": node["leaf_value"],
                    "weight": node.get("leaf_weight"),
                    "count": node.get("leaf_count")})
                return f"{tree_index}-L{node['leaf_index']}"
            idx = f"{tree_index}-S{node['split_index']}"
            row = {
                "tree_index": tree_index, "node_depth": depth,
                "node_index": idx, "parent_index": parent,
                "split_feature": names[node["split_feature"]],
                "split_gain": node["split_gain"],
                "threshold": node["threshold"],
                "decision_type": node["decision_type"],
                "missing_type": node.get("missing_type"),
                "value": node["internal_value"],
                "weight": node.get("internal_weight"),
                "count": node.get("internal_count")}
            pos = len(rows)
            rows.append(row)
            row["left_child"] = walk(tree_index, node["left_child"], idx,
                                     depth + 1)
            row["right_child"] = walk(tree_index, node["right_child"], idx,
                                      depth + 1)
            rows[pos] = row
            return idx

        for t in self.dump_model()["tree_info"]:
            walk(t["tree_index"], t["tree_structure"], None, 1)
        cols = ["tree_index", "node_depth", "node_index", "left_child",
                "right_child", "parent_index", "split_feature",
                "split_gain", "threshold", "decision_type", "missing_type",
                "value", "weight", "count"]
        return pd.DataFrame(rows, columns=cols)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        models = (self._gbdt.models if self._gbdt else self._loaded_trees)
        nfeat = self.num_feature()
        out = np.zeros(nfeat)
        k = self.num_model_per_iteration()
        if iteration is not None and iteration > 0:
            models = models[: iteration * k]
        for t in models:
            ni = t.num_leaves - 1
            for node in range(ni):
                f = t.split_feature[node]
                if importance_type == "split":
                    out[f] += 1
                else:
                    out[f] += max(float(t.split_gain[node]), 0.0)
        return out

    def num_feature(self) -> int:
        if self._gbdt is not None:
            return self._gbdt.train_data.num_total_features
        return int(self._loaded_meta.get("max_feature_idx", 0)) + 1

    def feature_name(self) -> List[str]:
        if "feature_names" in self._loaded_meta:
            return self._loaded_meta["feature_names"].split()
        if self._train_set is not None:
            return self._train_set.get_feature_names()
        return [f"Column_{i}" for i in range(self.num_feature())]

    def refit(self, data, label, weight=None, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Refit the existing tree structures on new data (reference
        Booster.refit -> LGBM_BoosterRefit -> GBDT::RefitTree, gbdt.cpp:285:
        leaf values are recomputed from the new data's gradients via
        FitByExistingTree and blended with refit_decay_rate)."""
        import jax.numpy as jnp
        from .config import Config as _Config
        from .objectives import create_objective

        data = np.asarray(data, dtype=np.float64)
        label = np.asarray(label, dtype=np.float64)
        n = data.shape[0]
        k = self.num_model_per_iteration()
        trees = (self._gbdt.models if self._gbdt else self._loaded_trees)
        if not trees:
            raise LightGBMError("refit requires a trained model")

        if self._gbdt is not None:
            cfg = self._gbdt.config
            obj = self._gbdt.objective
        else:
            params = dict(self.params)
            obj_str = self._loaded_meta.get("objective", "regression")
            params.setdefault("objective", obj_str.split()[0])
            if k > 1 and "num_class" not in params:
                params["num_class"] = k
            cfg = _Config(params)
            obj = create_objective(cfg)
        l1, l2 = float(cfg.lambda_l1), float(cfg.lambda_l2)

        w = (np.asarray(weight, np.float64) if weight is not None
             else np.ones(n))
        score = np.zeros((k, n))
        lbl = jnp.asarray(label)
        wgt = jnp.asarray(w)
        n_iter = len(trees) // k
        for it in range(n_iter):
            sc = jnp.asarray(score[0] if k == 1 else score)
            grad, hess = obj.get_gradients(sc, lbl, wgt)
            grad = np.atleast_2d(np.asarray(grad))
            hess = np.atleast_2d(np.asarray(hess))
            for cls in range(k):
                tree = trees[it * k + cls]
                leaf = tree.predict_leaf_index(data)
                nl = tree.num_leaves
                sum_g = np.bincount(leaf, weights=grad[cls], minlength=nl)
                sum_h = np.bincount(leaf, weights=hess[cls], minlength=nl)
                thr_g = np.sign(sum_g) * np.maximum(np.abs(sum_g) - l1, 0.0)
                new_out = -thr_g / (sum_h + l2 + 1e-15) * tree.shrinkage_
                tree.leaf_value[:nl] = (decay_rate * tree.leaf_value[:nl]
                                        + (1.0 - decay_rate) * new_out[:nl])
                score[cls] += tree.leaf_value[leaf]
        self._invalidate_stacked()
        return self

    # -- model io ---------------------------------------------------------
    def model_to_string(self, num_iteration: int = -1,
                        start_iteration: int = 0) -> str:
        # like the reference, default to best_iteration when early stopping
        # fired (python-package basic.py save_model num_iteration=None)
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        if self._gbdt is not None:
            return self._gbdt.save_model_to_string(start_iteration,
                                                   num_iteration)
        # re-serialize loaded model
        lines = [f"{k}={v}" for k, v in self._loaded_meta.items()
                 if k not in ("feature_names", "feature_infos")]
        header = ["tree"] + lines
        header.append("feature_names=" + self._loaded_meta.get("feature_names", ""))
        header.append("feature_infos=" + self._loaded_meta.get("feature_infos", ""))
        header.append("")
        for i, t in enumerate(self._loaded_trees):
            header.append(t.to_string(i))
        header.append("end of trees\n")
        return "\n".join(header)

    def save_model(self, filename: str, num_iteration: int = -1,
                   start_iteration: int = 0, **kwargs) -> "Booster":
        with open(filename, "w") as fh:
            fh.write(self.model_to_string(num_iteration, start_iteration))
        return self

    def dump_model(self, num_iteration: int = -1, start_iteration: int = 0,
                   importance_type: str = "split") -> dict:
        if num_iteration < 0 and self.best_iteration > 0:
            num_iteration = self.best_iteration
        models = (self._gbdt.models if self._gbdt else self._loaded_trees)
        k = self.num_model_per_iteration()
        trees = self._trees_for_range(start_iteration, num_iteration) \
            if models else []
        names = self.feature_name()
        imp = self.feature_importance(importance_type=importance_type,
                                      iteration=num_iteration)
        return {
            "name": "tree",
            "version": "v3",
            "num_class": k,
            "num_tree_per_iteration": k,
            "max_feature_idx": self.num_feature() - 1,
            "feature_names": names,
            # reference DumpModel always includes this section
            "feature_importances": {n: float(v)
                                    for n, v in zip(names, imp) if v > 0},
            "tree_info": [t.to_json(i) for i, t in enumerate(trees)],
        }

    def _load_from_string(self, model_str: str) -> None:
        header, _, rest = model_str.partition("\nTree=")
        meta: Dict[str, str] = {}
        for line in header.splitlines():
            if line.strip() == "average_output":
                meta["average_output"] = "1"
            elif "=" in line:
                key, v = line.split("=", 1)
                meta[key.strip()] = v.strip()
        self._loaded_meta = meta
        trees = []
        if rest:
            body = "Tree=" + rest
            blocks = body.split("\nTree=")
            for b in blocks:
                b = b.strip()
                if not b or b.startswith("end of trees"):
                    continue
                if not b.startswith("Tree="):
                    b = "Tree=" + b
                b = b.split("end of trees")[0]
                trees.append(Tree.from_string(b))
        self._loaded_trees = trees

    def __copy__(self):
        return self

    # reference Booster attributes used by callbacks
    @property
    def objective(self):
        if self._gbdt is not None:
            return self._gbdt.objective.name
        return self._loaded_meta.get("objective", "")


def _call_feval(feval, raw_np, data_meta):
    class _DS:  # minimal Dataset shim for feval signature
        def __init__(self, meta):
            self._meta = meta

        def get_label(self):
            return np.asarray(self._meta.label)

        def get_weight(self):
            return self._meta.weight

        def get_group(self):
            if self._meta.query_boundaries is None:
                return None
            return np.diff(self._meta.query_boundaries)

    fevals = feval if isinstance(feval, (list, tuple)) else [feval]
    out = []
    for f in fevals:
        r = f(raw_np, _DS(data_meta))
        if isinstance(r, list):
            out.extend(r)
        else:
            out.append(r)
    return out
