"""Flat-array decision tree model.

TPU-native equivalent of the reference ``Tree`` (include/LightGBM/tree.h,
src/io/tree.cpp).  The flat layout (parallel arrays indexed by internal-node id,
child pointers where ``>=0`` means internal node and ``<0`` means leaf ``~idx``)
carries over almost unchanged because it is already ideal for vectorized
traversal on device.  Text serialization keeps the reference's model format so
models interoperate with LightGBM tooling (src/io/tree.cpp:336 ToString).
"""

from __future__ import annotations

import numpy as np
from typing import Dict, List, Optional

__all__ = ["Tree"]

# decision_type_ bit layout (reference tree.h:15-21 masks)
K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
# missing type occupies bits 2-3: 0=None, 1=Zero, 2=NaN

_MISSING_CODE = {"none": 0, "zero": 1, "nan": 2}
_MISSING_NAME = {v: k for k, v in _MISSING_CODE.items()}

_K_ZERO_LOW, _K_ZERO_HIGH = -1e-35, 1e-35


class Tree:
    """A single decision tree with ``max_leaves`` capacity.

    ``num_leaves_`` grows as splits are applied; internal node ``i`` was created
    by the ``i``-th split (reference Tree::Split, tree.h:62).
    """

    def __init__(self, max_leaves: int):
        m = max_leaves
        self.max_leaves = m
        self.num_leaves = 1
        self.num_cat = 0
        self.left_child = np.zeros(m - 1, dtype=np.int32)
        self.right_child = np.zeros(m - 1, dtype=np.int32)
        self.split_feature = np.zeros(m - 1, dtype=np.int32)   # real feature idx
        self.threshold_in_bin = np.zeros(m - 1, dtype=np.int32)
        self.threshold = np.zeros(m - 1, dtype=np.float64)     # real-valued
        self.decision_type = np.zeros(m - 1, dtype=np.int8)
        self.split_gain = np.zeros(m - 1, dtype=np.float32)
        self.internal_value = np.zeros(m - 1, dtype=np.float64)
        self.internal_weight = np.zeros(m - 1, dtype=np.float64)
        self.internal_count = np.zeros(m - 1, dtype=np.int64)
        self.leaf_value = np.zeros(m, dtype=np.float64)
        self.leaf_weight = np.zeros(m, dtype=np.float64)
        self.leaf_count = np.zeros(m, dtype=np.int64)
        self.leaf_parent = np.full(m, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(m, dtype=np.int32)
        # categorical splits: threshold_in_bin indexes into cat boundaries
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []   # uint32 bitset words
        self.shrinkage_ = 1.0
        self.is_linear = False
        # linear leaves (reference linear_tree_learner; empty unless
        # linear_tree=true): output = leaf_const + sum coeff*x, NaN rows
        # fall back to leaf_value
        self.leaf_const = np.zeros(m, dtype=np.float64)
        self.leaf_features: List[List[int]] = [[] for _ in range(m)]
        self.leaf_coeff: List[List[float]] = [[] for _ in range(m)]

    # ------------------------------------------------------------------
    def split(self, leaf: int, feature: int, threshold_bin: int,
              threshold_double: float, left_value: float, right_value: float,
              left_cnt: int, right_cnt: int, left_weight: float,
              right_weight: float, gain: float, missing_type: str = "none",
              default_left: bool = False) -> int:
        """Numerical split of ``leaf``; returns the new (right) leaf id
        (reference Tree::Split, tree.h:62)."""
        new_node = self.num_leaves - 1
        new_leaf = self.num_leaves
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature[new_node] = feature
        self.threshold_in_bin[new_node] = threshold_bin
        self.threshold[new_node] = threshold_double
        dt = _MISSING_CODE[missing_type] << 2
        if default_left:
            dt |= K_DEFAULT_LEFT_MASK
        self.decision_type[new_node] = dt
        self.split_gain[new_node] = gain
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~new_leaf
        total_w = left_weight + right_weight
        self.internal_value[new_node] = (
            (left_value * left_weight + right_value * right_weight) / total_w
            if total_w > 0 else 0.0)
        self.internal_weight[new_node] = total_w
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_value[leaf] = left_value
        self.leaf_weight[leaf] = left_weight
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[new_leaf] = right_value
        self.leaf_weight[new_leaf] = right_weight
        self.leaf_count[new_leaf] = right_cnt
        depth = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] = depth
        self.leaf_depth[new_leaf] = depth
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[new_leaf] = new_node
        self.num_leaves += 1
        return new_leaf

    def split_categorical(self, leaf: int, feature: int, bin_bitset: List[int],
                          threshold_double_bitset: List[int],
                          left_value: float, right_value: float,
                          left_cnt: int, right_cnt: int, left_weight: float,
                          right_weight: float, gain: float) -> int:
        """Categorical split: rows whose category is in the bitset go left
        (reference Tree::SplitCategorical, tree.h:85).  Two bitsets are stored:
        one over bins (train-time) and one over raw category ids (predict)."""
        new_node = self.num_leaves - 1
        new_leaf = self.split(leaf, feature, 0, 0.0, left_value, right_value,
                              left_cnt, right_cnt, left_weight, right_weight,
                              gain, "none", False)
        self.decision_type[new_node] |= K_CATEGORICAL_MASK
        self.threshold_in_bin[new_node] = self.num_cat
        self.threshold[new_node] = self.num_cat
        self.num_cat += 1
        self.cat_boundaries.append(self.cat_boundaries[-1] + len(threshold_double_bitset))
        self.cat_threshold.extend(int(w) for w in threshold_double_bitset)
        if not hasattr(self, "cat_boundaries_inner"):
            self.cat_boundaries_inner: List[int] = [0]
            self.cat_threshold_inner: List[int] = []
        self.cat_boundaries_inner.append(self.cat_boundaries_inner[-1] + len(bin_bitset))
        self.cat_threshold_inner.extend(int(w) for w in bin_bitset)
        return new_leaf

    # ------------------------------------------------------------------
    def shrinkage(self, rate: float) -> None:
        n = self.num_leaves
        self.leaf_value[:n] *= rate
        self.internal_value[:max(n - 1, 0)] *= rate
        if self.is_linear:
            self.leaf_const[:n] *= rate
            for leaf in range(n):
                self.leaf_coeff[leaf] = [c * rate
                                         for c in self.leaf_coeff[leaf]]
        self.shrinkage_ *= rate

    def add_bias(self, val: float) -> None:
        n = self.num_leaves
        self.leaf_value[:n] += val
        self.internal_value[:max(n - 1, 0)] += val
        if self.is_linear:
            self.leaf_const[:n] += val
        self.shrinkage_ = 1.0

    def scale_leaf(self, leaf_values: np.ndarray) -> None:
        self.leaf_value[:self.num_leaves] = leaf_values[:self.num_leaves]

    def max_abs_leaf(self) -> float:
        """Largest |leaf value| this tree can contribute to any row —
        the per-tree term of the early-exit cascade's tail bound
        (ops.predict.tree_tail_bounds).  Leaf values store shrinkage
        in-place (see shrinkage()), so the bound needs no rate factor.
        Constant leaves only: a linear tree's contribution also depends
        on its per-leaf coefficients, so no finite per-tree bound exists
        here (the serving CompiledPredictor rejects linear trees)."""
        n = self.num_leaves
        if n <= 0:
            return 0.0
        return float(np.max(np.abs(self.leaf_value[:n])))

    # ------------------------------------------------------------------
    def _cat_in_bitset(self, node: int, ival: np.ndarray, inner: bool) -> np.ndarray:
        if inner:
            bounds, words = self.cat_boundaries_inner, self.cat_threshold_inner
        else:
            bounds, words = self.cat_boundaries, self.cat_threshold
        cat_idx = self.threshold_in_bin[node]
        lo, hi = bounds[cat_idx], bounds[cat_idx + 1]
        bits = np.asarray(words[lo:hi], dtype=np.uint32)
        word = ival >> 5
        ok = (ival >= 0) & (word < (hi - lo))
        word_c = np.clip(word, 0, max(hi - lo - 1, 0))
        return ok & (((bits[word_c] >> (ival & 31)) & 1) == 1)

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Host-side vectorized prediction over raw feature values
        (reference Tree::Predict -> NumericalDecision loop, tree.h:133,331;
        linear leaves: tree.h AddPredictionToScore<is_linear=true>)."""
        leaf = self.predict_leaf_index(X)
        out = self.leaf_value[leaf]
        if not self.is_linear:
            return out
        X = np.asarray(X, dtype=np.float64)
        for lf in range(self.num_leaves):
            coeffs = self.leaf_coeff[lf]
            rows = leaf == lf
            if not rows.any():
                continue
            if not coeffs:
                out[rows] = self.leaf_const[lf]
                continue
            feats = np.asarray(self.leaf_features[lf], np.int32)
            vals = X[np.ix_(rows, feats)]
            nanrow = np.isnan(vals).any(axis=1)
            lin = self.leaf_const[lf] + vals @ np.asarray(coeffs)
            out[rows] = np.where(nanrow, self.leaf_value[lf], lin)
        return out

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        n = X.shape[0]
        if self.num_leaves == 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = np.ones(n, dtype=bool)  # True while `node` refers to internal node
        leaf_out = np.zeros(n, dtype=np.int32)
        for _ in range(self.num_leaves):  # depth bound
            if not active.any():
                break
            idx = np.nonzero(active)[0]
            nd = node[idx]
            fval = X[idx, self.split_feature[nd]]
            dt = self.decision_type[nd]
            is_cat = (dt & K_CATEGORICAL_MASK) != 0
            go_left = np.zeros(len(idx), dtype=bool)
            # numerical decision
            num_mask = ~is_cat
            if num_mask.any():
                go_left[num_mask] = self._numerical_go_left(
                    nd[num_mask], fval[num_mask], dt[num_mask])
            if is_cat.any():
                iv = np.where(np.isnan(fval[is_cat]), -1,
                              fval[is_cat]).astype(np.int64)
                sub = np.zeros(int(is_cat.sum()), dtype=bool)
                for j, (nj, vj) in enumerate(zip(nd[is_cat], iv)):
                    sub[j] = bool(self._cat_in_bitset(int(nj),
                                                      np.asarray([vj]), False)[0])
                go_left[is_cat] = sub
            child = np.where(go_left, self.left_child[nd], self.right_child[nd])
            is_leaf = child < 0
            leaf_out[idx[is_leaf]] = ~child[is_leaf]
            node[idx[~is_leaf]] = child[~is_leaf]
            active[idx[is_leaf]] = False
        return leaf_out

    def _numerical_go_left(self, nodes, fval, dt) -> np.ndarray:
        missing = (dt.astype(np.int32) >> 2) & 3
        default_left = (dt & K_DEFAULT_LEFT_MASK) != 0
        thr = self.threshold[nodes]
        isnan = np.isnan(fval)
        iszero = (fval > _K_ZERO_LOW) & (fval < _K_ZERO_HIGH)
        # NaN with missing_type != nan is treated as 0 (reference tree.h:331-366)
        fval = np.where(isnan & (missing != 2), 0.0, fval)
        iszero = (fval > _K_ZERO_LOW) & (fval < _K_ZERO_HIGH)
        is_missing = ((missing == 2) & isnan) | ((missing == 1) & iszero)
        return np.where(is_missing, default_left, fval <= thr)

    # -- serialization ---------------------------------------------------
    def to_string(self, index: int) -> str:
        """Reference-format model text block (src/io/tree.cpp:336 ToString)."""
        n, ni = self.num_leaves, max(self.num_leaves - 1, 0)

        def arr(a, fmt="{:g}"):
            return " ".join(fmt.format(x) for x in a)

        lines = [f"Tree={index}",
                 f"num_leaves={n}",
                 f"num_cat={self.num_cat}",
                 f"split_feature={arr(self.split_feature[:ni], '{:d}')}",
                 f"split_gain={arr(self.split_gain[:ni])}",
                 f"threshold={arr(self.threshold[:ni], '{:.17g}')}",
                 f"decision_type={arr(self.decision_type[:ni], '{:d}')}",
                 f"left_child={arr(self.left_child[:ni], '{:d}')}",
                 f"right_child={arr(self.right_child[:ni], '{:d}')}",
                 f"leaf_value={arr(self.leaf_value[:n], '{:.17g}')}",
                 f"leaf_weight={arr(self.leaf_weight[:n], '{:.17g}')}",
                 f"leaf_count={arr(self.leaf_count[:n], '{:d}')}",
                 # full precision, NOT %g: pred_contrib reads
                 # internal_value/internal_weight as the per-node
                 # expected values, so a save/load round-trip must not
                 # drift a loaded model's explanations off the trained
                 # model's (predictions never read these, which is how
                 # the loss hid)
                 f"internal_value={arr(self.internal_value[:ni], '{:.17g}')}",
                 f"internal_weight={arr(self.internal_weight[:ni], '{:.17g}')}",
                 f"internal_count={arr(self.internal_count[:ni], '{:d}')}"]
        if self.num_cat > 0:
            lines.append(f"cat_boundaries={arr(self.cat_boundaries, '{:d}')}")
            lines.append(f"cat_threshold={arr(self.cat_threshold, '{:d}')}")
        lines.append(f"is_linear={int(self.is_linear)}")
        if self.is_linear:
            # reference linear-tree model keys (gbdt_model_text/tree.cpp):
            # leaf_const + per-leaf feature lists/coefficients, flattened
            # with per-leaf counts
            counts = [len(self.leaf_features[lf]) for lf in range(n)]
            flat_feats = [str(f) for lf in range(n)
                          for f in self.leaf_features[lf]]
            flat_coeff = ["{:.17g}".format(c) for lf in range(n)
                          for c in self.leaf_coeff[lf]]
            lines.append(f"leaf_const={arr(self.leaf_const[:n], '{:.17g}')}")
            lines.append("num_features=" + " ".join(str(c) for c in counts))
            lines.append("leaf_features=" + " ".join(flat_feats))
            lines.append("leaf_coeff=" + " ".join(flat_coeff))
        lines.append(f"shrinkage={self.shrinkage_:g}")
        lines.append("")
        return "\n".join(lines)

    @staticmethod
    def from_string(block: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in block.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        n = int(kv["num_leaves"])
        t = Tree(max(n, 2))
        t.num_leaves = n
        t.num_cat = int(kv.get("num_cat", 0))
        ni = max(n - 1, 0)

        def parse(key, dtype, count):
            if count == 0 or not kv.get(key):
                return np.zeros(count, dtype=dtype)
            vals = np.array([float(x) for x in kv[key].split()], dtype=np.float64)
            return vals.astype(dtype)

        t.split_feature[:ni] = parse("split_feature", np.int32, ni)
        t.split_gain[:ni] = parse("split_gain", np.float32, ni)
        t.threshold[:ni] = parse("threshold", np.float64, ni)
        t.decision_type[:ni] = parse("decision_type", np.int8, ni)
        t.left_child[:ni] = parse("left_child", np.int32, ni)
        t.right_child[:ni] = parse("right_child", np.int32, ni)
        t.leaf_value[:n] = parse("leaf_value", np.float64, n)
        t.leaf_weight[:n] = parse("leaf_weight", np.float64, n)
        t.leaf_count[:n] = parse("leaf_count", np.int64, n)
        t.internal_value[:ni] = parse("internal_value", np.float64, ni)
        t.internal_weight[:ni] = parse("internal_weight", np.float64, ni)
        t.internal_count[:ni] = parse("internal_count", np.int64, ni)
        if t.num_cat > 0:
            t.cat_boundaries = [int(float(x)) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(float(x)) for x in kv["cat_threshold"].split()]
            # categorical nodes store the cat-slot index in `threshold`
            # (reference tree.cpp ToString/Tree(const char*) round-trip)
            cat_nodes = (t.decision_type[:ni] & K_CATEGORICAL_MASK) != 0
            t.threshold_in_bin[:ni] = np.where(
                cat_nodes, t.threshold[:ni].astype(np.int32),
                t.threshold_in_bin[:ni])
        t.shrinkage_ = float(kv.get("shrinkage", 1.0))
        t.is_linear = bool(int(kv.get("is_linear", 0)))
        if t.is_linear:
            t.leaf_const[:n] = parse("leaf_const", np.float64, n)
            counts = [int(x) for x in kv.get("num_features", "").split()]
            feats = [int(x) for x in kv.get("leaf_features", "").split()]
            coeff = [float(x) for x in kv.get("leaf_coeff", "").split()]
            pos = 0
            for lf, c in enumerate(counts[:n]):
                t.leaf_features[lf] = feats[pos:pos + c]
                t.leaf_coeff[lf] = coeff[pos:pos + c]
                pos += c
        # rebuild leaf_parent and leaf_depth by walking from the root
        # (depth feeds stack_trees' traversal bound, ops/predict.py)
        if ni > 0:
            node_depth = np.zeros(ni, dtype=np.int32)
            stack = [0]
            while stack:
                node = stack.pop()
                for child in (t.left_child[node], t.right_child[node]):
                    if child < 0:
                        t.leaf_parent[~child] = node
                        t.leaf_depth[~child] = node_depth[node] + 1
                    else:
                        node_depth[child] = node_depth[node] + 1
                        stack.append(int(child))
        return t

    def to_json(self, index: int) -> dict:
        """JSON dump (reference Tree::ToJSON, src/io/tree.cpp:412)."""
        def node_json(node: int) -> dict:
            if node < 0:
                leaf = ~node
                return {"leaf_index": int(leaf),
                        "leaf_value": float(self.leaf_value[leaf]),
                        "leaf_weight": float(self.leaf_weight[leaf]),
                        "leaf_count": int(self.leaf_count[leaf])}
            dt = int(self.decision_type[node])
            if dt & K_CATEGORICAL_MASK:
                # reference Tree::ToJSON: "||"-joined category list
                cat_idx = int(self.threshold_in_bin[node])
                lo = self.cat_boundaries[cat_idx]
                hi = self.cat_boundaries[cat_idx + 1]
                cats = [str(32 * wi + b)
                        for wi, w in enumerate(self.cat_threshold[lo:hi])
                        for b in range(32) if (int(w) >> b) & 1]
                thr_json = "||".join(cats)
            else:
                thr_json = float(self.threshold[node])
            out = {
                "split_index": int(node),
                "split_feature": int(self.split_feature[node]),
                "split_gain": float(self.split_gain[node]),
                "threshold": thr_json,
                "decision_type": "==" if dt & K_CATEGORICAL_MASK else "<=",
                "default_left": bool(dt & K_DEFAULT_LEFT_MASK),
                "missing_type": _MISSING_NAME[(dt >> 2) & 3],
                "internal_value": float(self.internal_value[node]),
                "internal_weight": float(self.internal_weight[node]),
                "internal_count": int(self.internal_count[node]),
                "left_child": node_json(int(self.left_child[node])),
                "right_child": node_json(int(self.right_child[node])),
            }
            return out

        root = ~0 if self.num_leaves == 1 else 0
        return {"tree_index": index, "num_leaves": int(self.num_leaves),
                "num_cat": int(self.num_cat), "shrinkage": self.shrinkage_,
                "tree_structure": node_json(root)}

    # -- device export ---------------------------------------------------
    def to_arrays(self) -> dict:
        """Padded arrays for the device prediction kernel (ops/predict.py)."""
        return {
            "left_child": self.left_child,
            "right_child": self.right_child,
            "split_feature": self.split_feature,
            "threshold": self.threshold,
            "decision_type": self.decision_type,
            "leaf_value": self.leaf_value,
            "num_leaves": self.num_leaves,
        }
