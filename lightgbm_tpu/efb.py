"""Exclusive Feature Bundling (EFB): collapse mutually-exclusive sparse
features into shared bundles.

TPU-native counterpart of the reference's EFB pipeline
(src/io/dataset.cpp:53 GetConflictCount, :100 FindGroups, :239
FastFeatureBundling; FeatureGroup bin offsets, feature_group.h:25).  The
redesign for the MXU histogram formulation:

- STORAGE and the HISTOGRAM PASS run at bundle width: the device bin matrix
  is ``uint8[N, n_bundles]`` and one histogram pass costs
  O(N * n_bundles * B) instead of O(N * F * B) — this is where the 4x+
  win on one-hot-heavy data (Criteo/Bosch/Allstate) comes from.
- The SPLIT SCAN runs in original-feature space: each leaf's bundle
  histogram is expanded on device to per-member histograms
  (``expand_bundle_hist``) with the member's zero-bin reconstructed as
  ``leaf_total - sum(member nonzero bins)``.  Split semantics are therefore
  IDENTICAL to unbundled training (the reference achieves the same by
  scanning each member's bin sub-range inside the FeatureGroup).
- Partition / traversal decode a member's bin as
  ``bin = bundle_bin - offset if offset < bundle_bin < offset + num_bin
  else 0`` (zero bin) — branch-free and gather-free beyond the one bundled
  column read.

Bundling eligibility (v1, documented deviations from the reference):
only numerical features with no missing bin whose raw value 0.0 maps to
bin 0 (the one-hot / sparse-counter shape EFB exists for).  Categorical and
missing-capable features keep singleton bundles.  Conflict budget follows
the reference: ``total_sample_cnt / 10000`` shared-nonzero rows.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np
import jax.numpy as jnp

__all__ = ["BundleMap", "find_bundles", "bundle_rows", "bundle_widths",
           "make_bundle_map", "expand_bundle_hist"]


class BundleMap(NamedTuple):
    """Per-original-feature decode table.  Only device arrays live here so
    the tuple can ride through jit as a pytree; the static bundle count /
    bin width go into GrowerConfig (num_bins) instead."""
    bundle_of_f: jnp.ndarray    # [F] int32: which bundled column
    offset_of_f: jnp.ndarray    # [F] int32: bin offset inside the bundle
    is_bundled_f: jnp.ndarray   # [F] bool: True if sharing a bundle (needs
    #                             zero-bin reconstruction)


def _eligible(mapper, is_cat: bool) -> bool:
    if is_cat or mapper.missing_bin is not None:
        return False
    try:
        return int(np.asarray(mapper.value_to_bin(np.zeros(1)))[0]) == 0
    except Exception:
        return False


def find_bundles(bins: np.ndarray, mappers, is_categorical,
                 max_bin: int, sample_rows: int = 50_000,
                 seed: int = 0) -> List[List[int]]:
    """Greedy conflict-bounded grouping (reference FindGroups,
    dataset.cpp:100): visit features by nonzero count descending, add each
    to the first bundle whose conflict count stays under budget and whose
    total bin width stays <= max_bin; else open a new bundle."""
    n, f = bins.shape
    if sample_rows < n:
        rng = np.random.RandomState(seed)
        idx = rng.choice(n, size=sample_rows, replace=False)
        sample = bins[np.sort(idx)]
    else:
        sample = bins
    s = sample.shape[0]
    budget = s // 10000  # reference single_val_max_conflict_cnt
    nz = sample != 0                      # [S, F] bool
    nnz = nz.sum(axis=0)
    # bit-pack occupancy so conflict counting is popcount over S/8 bytes,
    # not a dense [S]-bool AND (matters on the wide one-hot data EFB
    # targets); cap the bundles searched per feature like the reference
    # caps its group search (FindGroups max_search_group)
    nzp = np.packbits(nz, axis=0)         # [ceil(S/8), F] uint8
    popcnt = np.unpackbits(np.arange(256, dtype=np.uint8)[:, None],
                           axis=1).sum(axis=1).astype(np.int32)
    max_search = 256

    eligible = np.asarray([_eligible(m, bool(c))
                           for m, c in zip(mappers, is_categorical)])
    order = np.argsort(-nnz, kind="stable")

    bundles: List[List[int]] = []
    bundle_occ: List[np.ndarray] = []     # packed occupancy per bundle
    bundle_conflict: List[int] = []
    bundle_width: List[int] = []          # 1 + sum(num_bin - 1)
    searchable: List[int] = []            # indices of joinable bundles
    for fi in order:
        fi = int(fi)
        if not eligible[fi]:
            bundles.append([fi])
            bundle_occ.append(None)
            bundle_conflict.append(0)
            bundle_width.append(0)
            continue
        w = mappers[fi].num_bin - 1
        col = nzp[:, fi]
        placed = False
        for b in searchable[:max_search]:
            if bundle_width[b] + w > max_bin:
                continue
            conf = int(popcnt[col & bundle_occ[b]].sum())
            if bundle_conflict[b] + conf <= budget:
                bundles[b].append(fi)
                bundle_occ[b] |= col
                bundle_conflict[b] += conf
                bundle_width[b] += w
                placed = True
                break
        if not placed:
            searchable.append(len(bundles))
            bundles.append([fi])
            bundle_occ.append(col.copy())
            bundle_conflict.append(0)
            bundle_width.append(1 + w)
    return bundles


def make_bundle_map(bundles: List[List[int]], mappers,
                    num_features: int):
    """Returns (BundleMap, num_bundles, max_bundle_bins)."""
    bundle_of = np.zeros(num_features, np.int32)
    offset_of = np.zeros(num_features, np.int32)
    is_bundled = np.zeros(num_features, bool)
    max_bins = 1
    for g, members in enumerate(bundles):
        shared = len(members) > 1
        off = 0
        for fi in members:
            bundle_of[fi] = g
            offset_of[fi] = off
            is_bundled[fi] = shared
            if shared:
                off += mappers[fi].num_bin - 1
            else:
                off = 0
        width = (1 + off) if shared else mappers[members[0]].num_bin
        max_bins = max(max_bins, width)
    bmap = BundleMap(bundle_of_f=jnp.asarray(bundle_of),
                     offset_of_f=jnp.asarray(offset_of),
                     is_bundled_f=jnp.asarray(is_bundled))
    return bmap, len(bundles), int(max_bins)


def bundle_widths(bundles: List[List[int]], mappers) -> List[int]:
    """Per-bundle device-column bin count: a singleton keeps its member's
    num_bin; a shared bundle packs each member's nonzero range after bin 0
    (the histogram width-class planner keys off these widths)."""
    widths = []
    for members in bundles:
        if len(members) == 1:
            widths.append(mappers[members[0]].num_bin)
        else:
            widths.append(1 + sum(mappers[fi].num_bin - 1 for fi in members))
    return widths


def bundle_rows(bins: np.ndarray, bundles: List[List[int]], mappers,
                out_dtype=None) -> np.ndarray:
    """Re-encode a per-feature bin matrix [N, F] into bundle space [N, G].

    Conflicting rows (>1 member nonzero) keep the LAST member pushed —
    mirroring the reference's overwrite-on-push semantics
    (FeatureGroup::PushData)."""
    n = bins.shape[0]
    g = len(bundles)
    widths = bundle_widths(bundles, mappers)
    if out_dtype is None:
        out_dtype = np.uint8 if max(widths) <= 256 else np.int32
    out = np.zeros((n, g), out_dtype)
    for gi, members in enumerate(bundles):
        if len(members) == 1:
            out[:, gi] = bins[:, members[0]]
            continue
        off = 0
        for fi in members:
            col = bins[:, fi].astype(np.int64)
            nzr = col != 0
            out[nzr, gi] = (off + col[nzr]).astype(out_dtype)
            off += mappers[fi].num_bin - 1
    return out


def decode_member_bin(col, offset, num_bins):
    """Member-feature bin from a bundle-column value: bins 1..num_bins-1 map
    from [offset+1, offset+num_bins), anything else is the zero bin.  The
    single source of truth shared by train-time partition
    (tree_learner.py) and predict-time traversal (ops/predict.py) — the
    inverse of bundle_rows' encode."""
    return jnp.where((col > offset) & (col < offset + num_bins),
                     col - offset, 0)


def expand_bundle_hist(hist_g: jnp.ndarray, leaf_total: jnp.ndarray,
                       bmap: BundleMap, num_bins_f: jnp.ndarray,
                       num_bins_out: int) -> jnp.ndarray:
    """[G, Bg, C] bundle histogram -> [F, B, C] per-member histograms.

    Member bin b>=1 reads bundle bin offset+b; member bin 0 (the zero bin)
    is reconstructed as leaf_total - sum(nonzero member bins) for shared
    bundles; singleton bundles pass through unchanged.  Pure gathers over a
    [G*Bg] table — O(F*B) VPU work, negligible next to the histogram pass.
    """
    b = num_bins_out
    bidx = jnp.arange(b, dtype=jnp.int32)[None, :]          # [1, B]
    src_bin = bmap.offset_of_f[:, None] + bidx              # [F, B]
    in_range = (bidx >= 1) & (bidx < num_bins_f[:, None])
    src_bin = jnp.clip(src_bin, 0, hist_g.shape[1] - 1)
    gathered = hist_g[bmap.bundle_of_f[:, None], src_bin]   # [F, B, C]

    shared = bmap.is_bundled_f[:, None, None]
    # shared members: nonzero bins from the gather, zero bin reconstructed
    nonzero_part = jnp.where(in_range[:, :, None], gathered, 0.0)
    zero_stat = leaf_total[None, :] - nonzero_part.sum(axis=1)  # [F, C]
    at_zero = (jnp.arange(b, dtype=jnp.int32) == 0)[None, :, None]
    shared_hist = jnp.where(at_zero, zero_stat[:, None, :], nonzero_part)

    # singleton members: direct passthrough of their bundle's bins
    valid = (bidx < num_bins_f[:, None])[:, :, None]
    solo_hist = jnp.where(valid, gathered, 0.0)
    return jnp.where(shared, shared_hist, solo_hist)
