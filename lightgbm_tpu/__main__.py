"""``python -m lightgbm_tpu`` — the CLI entry point (reference src/main.cpp)."""

import os
import sys


def _pin_platform() -> None:
    """Honor LIGHTGBM_TPU_PLATFORM through the jax config API.

    A site-wide ``sitecustomize`` may pre-import jax and point it at an
    accelerator plugin before this process's environment is consulted; on a
    shared machine that can block the CLI on an exclusive-device claim.
    Re-pinning via jax.config wins over the pre-import (same pattern as
    tests/conftest.py)."""
    want = os.environ.get("LIGHTGBM_TPU_PLATFORM")
    if want:
        import jax
        jax.config.update("jax_platforms", want)


if __name__ == "__main__":
    _pin_platform()
    from .application import main
    sys.exit(main())
