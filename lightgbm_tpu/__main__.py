"""``python -m lightgbm_tpu`` — the CLI entry point (reference src/main.cpp)."""

import sys

from .application import main

if __name__ == "__main__":
    sys.exit(main())
