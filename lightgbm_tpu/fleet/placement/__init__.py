"""Multi-tenant control plane: placement + autoscaling over the fleet.

The data plane (router + replicas) can host hundreds of models per
replica because the serving tier's predict programs are shared through
the tree-bucket ladder (serving/compiled.py): executables are keyed by
bucketed geometry, never by one model's weights, so publishing model
number 300 — or a continuation delta of model 3 — costs zero compiles.
This package is the control plane on top of that substrate:

- ``PlacementController`` (controller.py) — reads the router's
  per-model SLO gauges and replica capacity, computes a target
  model->replica assignment (bin-pack by goodput with headroom, spread
  hot models), and converges the fleet to it with idempotent
  token-carrying per-replica publishes, an atomic routing-table flip
  per move, and a drain window so the old replica serves until the new
  one has proven it can.
- ``FleetAutoscaler`` (autoscale.py) — grows/shrinks the supervised
  replica set against aggregate deadline-miss ratio and fleet goodput,
  with consecutive-poll hysteresis and a cooldown, reusing
  ``FleetSupervisor``'s slot machinery for spawn/retire.

CLI: ``fleet_placement=true`` wires the controller into ``serve_fleet``;
``fleet_autoscale_max_replicas>0`` wires the autoscaler (see config.py
for the full ``fleet_placement_*`` / ``fleet_autoscale_*`` knob table).
"""

from __future__ import annotations

from .autoscale import FleetAutoscaler
from .controller import PlacementController

__all__ = ["PlacementController", "FleetAutoscaler"]
