"""PlacementController: converge the fleet to a model->replica target.

The router owns the placement TABLE (model -> replica indices, consulted
per request); this controller owns the placement DECISION and the
migration PROTOCOL:

- **decide** (``compute_target``): bin-pack models onto live replicas by
  recent goodput with configurable headroom, cap models per replica, and
  spread hot models (goodput above the spread threshold) across two
  replicas.  The packing is sticky — a model keeps its current replicas
  whenever they still fit — so a stable fleet sees zero moves per poll.
- **converge** (``place`` / ``poll_once``): for each model whose current
  set differs from the target, publish it to the missing replicas using
  the registry's idempotent publish tokens (a move interrupted anywhere
  re-sends the SAME token on retry, so the destination can never
  double-apply), verify each destination answers a warmup probe, flip
  the router's table atomically to the union (old AND new serve), wait
  out a drain window, flip to the target, and only then unpublish the
  surplus replicas.  A failed step leaves the table untouched — the next
  poll retries from wherever the move died.

Every move is a traced span plus
``lgbm_fleet_placement_{moves,failed_moves}_total``; the controller runs
on its own daemon thread (``start``), or tests drive ``poll_once``
directly.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Set

from ...log import log_info, log_warning
from ...telemetry import trace as _trace
from ..router import ReplicaTransportError

__all__ = ["PlacementController"]


class PlacementController:
    def __init__(self, router, max_models_per_replica: int = 64,
                 headroom: float = 0.2,
                 capacity_rows_s: float = 50_000.0,
                 spread_rows_s: float = 0.0,
                 drain_ms: float = 500.0,
                 poll_ms: float = 2000.0,
                 max_moves_per_poll: int = 4,
                 registry=None, tracer=None):
        self.router = router
        self.max_models_per_replica = max(int(max_models_per_replica), 1)
        self.headroom = min(max(float(headroom), 0.0), 0.95)
        self.capacity_rows_s = max(float(capacity_rows_s), 1.0)
        # a model whose goodput exceeds this is "hot" and spread across
        # two replicas; 0 = auto (half of one replica's usable capacity)
        usable = self.capacity_rows_s * (1.0 - self.headroom)
        self.spread_rows_s = (float(spread_rows_s) if spread_rows_s > 0
                              else usable / 2.0)
        self.drain_s = max(float(drain_ms), 0.0) / 1e3
        self.poll_interval_s = max(float(poll_ms), 0.0) / 1e3
        self.max_moves_per_poll = max(int(max_moves_per_poll), 1)
        self.tracer = tracer if tracer is not None else _trace.TRACER
        reg = registry if registry is not None else router.registry
        self._m_moves = reg.counter(
            "lgbm_fleet_placement_moves_total",
            "placement convergence steps that fully landed (publish to "
            "new replicas, drained table flip, surplus unpublished)")
        self._m_failed = reg.counter(
            "lgbm_fleet_placement_failed_moves_total",
            "placement moves abandoned mid-protocol (routing table left "
            "untouched; retried with the same publish token next poll)")
        self._g_placed = reg.gauge(
            "lgbm_fleet_placement_placed_models",
            "models with an explicit placement entry (narrowed from the "
            "broadcast-everywhere default)")
        # (model, dst_idx) -> publish token: a move that died after its
        # publish may have landed on the destination — the retry MUST
        # re-send the same token so the registry replays the version it
        # already minted instead of installing a duplicate
        self._move_tokens: Dict[tuple, str] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------------
    # decide
    # ------------------------------------------------------------------
    def compute_target(self, table: Optional[Dict] = None,
                       live: Optional[List[int]] = None
                       ) -> Dict[str, Set[int]]:
        """Pure assignment pass: {model: target replica indices}.

        Models are packed hottest-first onto the live replicas; each
        placement charges the replica the model's per-replica goodput
        share.  Stickiness: a replica already hosting the model wins
        ties, so the target only differs from the current table when
        load or topology actually changed."""
        router = self.router
        live = sorted(live if live is not None else router.live_indices())
        if not live:
            return {}
        table = table if table is not None else router.model_table()
        usable = self.capacity_rows_s * (1.0 - self.headroom)
        load = {i: 0.0 for i in live}
        count = {i: 0 for i in live}

        def goodput(row):
            slo = row.get("slo") or {}
            return float(slo.get("goodput_rows_per_s") or 0.0)

        target: Dict[str, Set[int]] = {}
        for name, row in sorted(table.items(),
                                key=lambda kv: -goodput(kv[1])):
            g = goodput(row)
            want_n = min(2 if g >= self.spread_rows_s else 1, len(live))
            cur = router.placement(name) & set(live)
            share = g / want_n

            def cost(i):
                # sticky first, then least goodput-loaded, then fewest
                # models; index last for determinism
                return (i not in cur, load[i], count[i], i)

            chosen: List[int] = []
            for i in sorted(live, key=cost):
                if count[i] >= self.max_models_per_replica:
                    continue
                if load[i] + share > usable:
                    continue
                chosen.append(i)
                load[i] += share
                count[i] += 1
                if len(chosen) >= want_n:
                    break
            if not chosen:
                # everything is over capacity: availability beats the
                # packing constraint — place on the least-loaded replica
                i = min(live, key=lambda j: (load[j], count[j], j))
                chosen = [i]
                load[i] += share
                count[i] += 1
            target[name] = set(chosen)
        return target

    # ------------------------------------------------------------------
    # converge
    # ------------------------------------------------------------------
    def _endpoint(self, idx: int):
        return self.router._replicas[idx].endpoint

    def _publish_to(self, name: str, dst: int, body: dict) -> Optional[int]:
        """Idempotent targeted publish + warmup probe.  Returns the
        installed version, or None on failure (token retained for the
        retry)."""
        token = self._move_tokens.setdefault((name, dst),
                                             uuid.uuid4().hex)
        body = dict(body)
        body["publish_token"] = token
        ep = self._endpoint(dst)
        try:
            status, payload = ep.request(
                "POST", f"/v1/models/{name}:publish", body,
                timeout_s=self.router.request_timeout_s)
        except ReplicaTransportError as exc:
            log_warning(f"placement: publish of {name!r} to {ep.name} "
                        f"failed: {exc}")
            return None
        if status != 200:
            log_warning(f"placement: publish of {name!r} to {ep.name} "
                        f"refused (status {status})")
            return None
        # warmup probe: the destination must ANSWER for the model before
        # any traffic shifts — publish warms the ladder pre-swap, so the
        # registry listing doubles as "loaded, warmed, current"
        try:
            st, listing = ep.request("GET", "/v1/models", None,
                                     timeout_s=self.router.health_timeout_s)
        except ReplicaTransportError:
            st, listing = 0, {}
        if st != 200 or name not in (listing.get("models") or {}):
            log_warning(f"placement: {ep.name} does not list {name!r} "
                        f"after publish — move aborted")
            return None
        return payload.get("version")

    def place(self, name: str, want, drain: bool = True) -> bool:
        """Converge one model to replica set ``want``: publish where
        missing (probed), atomically widen the routing table to old+new,
        drain, narrow to ``want``, then unpublish the surplus.  Returns
        False (and counts a failed move) the moment any destination
        cannot be brought up — with the table untouched, so in-flight
        and future requests keep landing on replicas that have the
        model."""
        router = self.router
        want = {int(i) for i in want}
        live = set(router.live_indices())
        want &= live
        if not want:
            return False
        have = router.placement(name) & live
        if want == have:
            return True
        tspan = self.tracer.start_request(
            "placement.move", model=name, src=sorted(have),
            dst=sorted(want))
        try:
            missing = want - have
            if missing:
                body = router.published_body(name)
                if body is None:
                    # nothing to replay: the model was never published
                    # through this router (or was rolled back) — narrowing
                    # is still legal, widening is not
                    if tspan is not None:
                        tspan.event("placement.no_publish_body")
                    self._m_failed.inc()
                    return False
                for dst in sorted(missing):
                    version = self._publish_to(name, dst, body)
                    if version is None:
                        if tspan is not None:
                            tspan.event("placement.publish_failed",
                                        replica=self._endpoint(dst).name)
                        self._m_failed.inc()
                        return False
                    if isinstance(version, int):
                        router.note_version(name, version)
                    if tspan is not None:
                        tspan.event("placement.published",
                                    replica=self._endpoint(dst).name,
                                    version=version)
            # both old and new serve during the drain: requests already
            # routed to the old set finish there, new ones spread
            router.set_placement(name, want | have)
            if drain and (have - want) and self.drain_s > 0:
                if tspan is not None:
                    tspan.event("placement.drain",
                                ms=round(self.drain_s * 1e3, 1))
                time.sleep(self.drain_s)
            router.set_placement(name, want)
            for src in sorted(have - want):
                ep = self._endpoint(src)
                try:
                    ep.request("POST", f"/v1/models/{name}:unpublish",
                               None, timeout_s=router.request_timeout_s)
                    if tspan is not None:
                        tspan.event("placement.unpublished",
                                    replica=ep.name)
                except ReplicaTransportError as exc:
                    # non-fatal: the model stays resident but unrouted
                    # on src; the rejoin replay is placement-filtered so
                    # it can never come back through that path
                    log_warning(f"placement: unpublish of {name!r} on "
                                f"{ep.name} failed: {exc}")
            for dst in sorted(missing if missing else set()):
                self._move_tokens.pop((name, dst), None)
            self._m_moves.inc()
            log_info(f"placement: {name!r} moved {sorted(have)} -> "
                     f"{sorted(want)}")
            return True
        finally:
            if tspan is not None:
                tspan.finish_request(status=200)

    def move(self, name: str, src: int, dst: int) -> bool:
        """One-model migration convenience: replace ``src`` with ``dst``
        in the model's replica set (the bench's mid-soak hot-model
        move)."""
        have = self.router.placement(name)
        return self.place(name, (have - {int(src)}) | {int(dst)})

    def drain_replica(self, idx: int) -> bool:
        """Move every model placed on ``idx`` elsewhere (scale-down
        preamble).  Models still on the broadcast-everywhere default are
        untouched — retiring the slot removes it from their route set
        automatically.  Returns False if any move failed."""
        idx = int(idx)
        ok = True
        live = [i for i in self.router.live_indices() if i != idx]
        if not live:
            return False
        for name, row in self.router.model_table().items():
            if not row.get("placed"):
                continue
            have = self.router.placement(name)
            if idx not in have:
                continue
            want = have - {idx}
            if not want:
                want = {min(live, key=lambda j: (
                    self.router._replicas[j].load_rows, j))}
            ok = self.place(name, want) and ok
        return ok

    def poll_once(self) -> int:
        """One control-loop step: recompute the target and apply up to
        ``max_moves_per_poll`` convergence moves.  Returns the number of
        models moved."""
        target = self.compute_target()
        with self.router._lock:
            self._g_placed.set(len(self.router._placement))
        moved = 0
        for name, want in target.items():
            if moved >= self.max_moves_per_poll:
                break
            if want != self.router.placement(name):
                if self.place(name, want):
                    moved += 1
        return moved

    # ------------------------------------------------------------------
    def start(self) -> "PlacementController":
        if self._thread is None and self.poll_interval_s > 0:
            def _loop():
                while not self._stop.wait(self.poll_interval_s):
                    try:
                        self.poll_once()
                    except Exception as exc:   # control loop never dies
                        log_warning(
                            f"placement: poll failed: {exc!r}")

            self._thread = threading.Thread(
                target=_loop, name="lgbm-tpu-fleet-placement",
                daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "PlacementController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
