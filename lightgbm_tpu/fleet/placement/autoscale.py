"""FleetAutoscaler: grow/shrink the supervised replica set under load.

Signals (read per poll from the router's recent-evidence windows — the
same feed the placement controller uses):

- **aggregate deadline-miss ratio** — the fraction of recent requests
  across ALL models that ended 504.  Sustained misses mean the fleet
  cannot meet its deadlines at the current size: scale UP.
- **fleet goodput vs. capacity** — rows/s answered 200, against the
  configured per-replica capacity.  When one fewer replica would still
  carry the load with the placement headroom intact AND nothing is
  missing deadlines: scale DOWN.

Both directions use consecutive-poll hysteresis (``polls`` agreeing
polls before any action) and a shared cooldown, so one burst cannot
flap the fleet.  Scale-up reuses ``FleetSupervisor.add_slot`` (same
argv, same restart budget), waits for the new replica's /healthz,
registers it with the router, and replays the fleet's published models
to it (placement-filtered) so it can serve before the controller ever
touches it.  Scale-down drains the victim through the placement
controller first (every placed model moved off), then retires the slot
on both the router (out of rotation, atomically) and the supervisor
(process terminated, never respawned).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from ...log import log_info, log_warning
from ..router import HttpReplica, ReplicaTransportError

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    def __init__(self, supervisor, router, controller=None,
                 min_replicas: int = 1, max_replicas: int = 4,
                 miss_ratio_high: float = 0.05,
                 capacity_rows_s: float = 50_000.0,
                 headroom: float = 0.2,
                 polls: int = 3, cooldown_s: float = 30.0,
                 poll_ms: float = 2000.0,
                 ready_timeout_s: float = 180.0,
                 registry=None):
        self.supervisor = supervisor
        self.router = router
        self.controller = controller   # optional: drains before retire
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.miss_ratio_high = float(miss_ratio_high)
        self.capacity_rows_s = max(float(capacity_rows_s), 1.0)
        self.headroom = min(max(float(headroom), 0.0), 0.95)
        self.polls = max(int(polls), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.poll_interval_s = max(float(poll_ms), 0.0) / 1e3
        self.ready_timeout_s = float(ready_timeout_s)
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = registry if registry is not None else router.registry
        self._m_up = reg.counter(
            "lgbm_fleet_autoscale_up_total",
            "replica slots added by the autoscaler")
        self._m_down = reg.counter(
            "lgbm_fleet_autoscale_down_total",
            "replica slots drained and retired by the autoscaler")
        self._m_failed = reg.counter(
            "lgbm_fleet_autoscale_failed_total",
            "autoscale actions that did not complete (spawn never became "
            "ready, or the drain could not move every placed model)")
        self._g_replicas = reg.gauge(
            "lgbm_fleet_replicas",
            "live (non-retired) replica slots")
        self._g_replicas.set(len(router.live_indices()))

    # ------------------------------------------------------------------
    def signals(self) -> Tuple[float, float]:
        """(aggregate deadline-miss ratio, fleet goodput rows/s) over the
        router's recent-evidence windows."""
        miss_num = miss_den = goodput = 0.0
        for mm in list(self.router._per_model.values()):
            miss_num += mm.outcomes.window_sum()
            miss_den += mm.outcomes.window_count()
            goodput += mm.rows.window_sum() / (mm.rows.window_s or 1.0)
        return (miss_num / miss_den if miss_den else 0.0), goodput

    def poll_once(self) -> str:
        """One hysteresis step.  Returns the action taken:
        'up' / 'down' / 'hold'."""
        live = self.router.live_indices()
        self._g_replicas.set(len(live))
        if time.time() < self._cooldown_until:
            return "hold"
        miss, goodput = self.signals()
        usable = self.capacity_rows_s * (1.0 - self.headroom)
        want_up = miss > self.miss_ratio_high and len(live) < \
            self.max_replicas
        # scale down only when the fleet is comfortably meeting
        # deadlines AND one fewer replica still fits the load under the
        # same headroom the packer plans with
        want_down = (miss <= self.miss_ratio_high / 4.0
                     and len(live) > self.min_replicas
                     and goodput < usable * (len(live) - 1))
        self._up_streak = self._up_streak + 1 if want_up else 0
        self._down_streak = self._down_streak + 1 if want_down else 0
        if self._up_streak >= self.polls:
            self._up_streak = self._down_streak = 0
            self._cooldown_until = time.time() + self.cooldown_s
            return "up" if self.scale_up() else "hold"
        if self._down_streak >= self.polls:
            self._up_streak = self._down_streak = 0
            self._cooldown_until = time.time() + self.cooldown_s
            return "down" if self.scale_down() else "hold"
        return "hold"

    # ------------------------------------------------------------------
    def scale_up(self) -> bool:
        """Spawn one replica slot, wait for /healthz, register it with
        the router, replay published models to it."""
        sup = self.supervisor
        try:
            slot = sup.add_slot()
        except Exception as exc:
            log_warning(f"autoscale: spawn failed: {exc!r}")
            self._m_failed.inc()
            return False
        url = f"{sup.host}:{sup.replicas[slot].port}"
        ep = HttpReplica(url)
        deadline = time.time() + self.ready_timeout_s
        ready = False
        while time.time() < deadline and not self._stop.is_set():
            try:
                status, _ = ep.request("GET", "/healthz", timeout_s=2.0)
                if status == 200:
                    ready = True
                    break
            except ReplicaTransportError:
                pass
            if not sup.replicas[slot].alive and sup.replicas[slot].gave_up:
                break
            time.sleep(0.25)
        if not ready:
            log_warning(f"autoscale: new replica {url} never became "
                        f"ready; retiring the slot")
            sup.retire_slot(slot)
            self._m_failed.inc()
            return False
        router = self.router
        idx = router.add_replica(ep)
        # the new replica spawned from the ORIGINAL argv: hot-swaps it
        # never saw must be replayed (placement-filtered — models placed
        # on other replicas stay off this one) before it takes traffic
        # for them; unplaced models route here immediately
        with router._lock:
            published = {n: dict(b) for n, b in router._published.items()
                         if router._placement.get(n) is None}
        if published:
            router._replay_publishes(router._replicas[idx], published)
        self._m_up.inc()
        self._g_replicas.set(len(router.live_indices()))
        log_info(f"autoscale: scaled up — replica {url} is slot {idx}")
        return True

    def scale_down(self) -> bool:
        """Drain and retire the highest-index live slot."""
        router = self.router
        live = router.live_indices()
        if len(live) <= self.min_replicas:
            return False
        victim = max(live)
        if self.controller is not None:
            if not self.controller.drain_replica(victim):
                log_warning(f"autoscale: drain of slot {victim} "
                            f"incomplete; holding")
                self._m_failed.inc()
                return False
        router.retire_replica(victim)
        sup = self.supervisor
        if victim < len(sup.replicas):
            sup.retire_slot(victim)
        self._m_down.inc()
        self._g_replicas.set(len(router.live_indices()))
        log_info(f"autoscale: scaled down — slot {victim} retired")
        return True

    # ------------------------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        if self._thread is None and self.poll_interval_s > 0:
            def _loop():
                while not self._stop.wait(self.poll_interval_s):
                    try:
                        self.poll_once()
                    except Exception as exc:   # control loop never dies
                        log_warning(f"autoscale: poll failed: {exc!r}")

            self._thread = threading.Thread(
                target=_loop, name="lgbm-tpu-fleet-autoscale",
                daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "FleetAutoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
