"""Replica supervision: spawn N serving processes, restart the dead ones.

cluster.py supervises TRAINING workers under a synchronous-SPMD fault
model (one death fails the job, recovery = relaunch everyone from a
checkpoint).  Serving replicas are the opposite: independent, stateless
(their state is a model file plus an AOT bundle on disk), so the right
recovery is per-replica — when one dies, the other replicas keep serving
(the router routes around the corpse) and only the dead one is relaunched,
with the same bounded exponential backoff and restart budget as the
training supervisor.  A relaunched replica cold-starts warm: it reloads
its models from the same files and deserializes its predict programs from
the shared AOT bundle, so it rejoins with zero compiles.

Fault injection follows the LGBM_TPU_FAULT_ITER pattern
(checkpoint/fault.py): ``fault_env={"LGBM_TPU_FAULT_REQUEST": "500"}`` on
one replica makes it kill itself mid-soak, and — like cluster.py — the
fault env is STRIPPED on restart attempts, modelling a transient
preemption.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..checkpoint.fault import FAULT_ENV_VARS
from ..log import log_info, log_warning

__all__ = ["FleetSupervisor", "ReplicaProc"]


class ReplicaProc:
    """One supervised replica slot (the process may be reincarnated)."""

    def __init__(self, idx: int, port: int):
        self.idx = idx
        self.port = port
        self.proc: Optional[subprocess.Popen] = None
        self.attempt = 0              # spawn generation (0 = first launch)
        self.restarts = 0
        self.next_spawn_at = 0.0      # backoff deadline for the respawn
        self.log_paths: List[str] = []
        self.gave_up = False

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class FleetSupervisor:
    """Spawn + babysit one serving process per replica slot.

    ``make_argv(idx, port) -> List[str]`` builds each replica's command
    line (the CLI fleet path passes ``task=serve fleet_role=replica``
    plus the shared model/bundle params).  ``watch()`` is the supervision
    step — poll it from a loop (or let ``start_watching`` run it on a
    thread): dead replicas respawn after ``restart_backoff_s * 2**n``
    with fault env stripped, up to ``max_restarts`` per replica, after
    which the slot is abandoned (logged; the router keeps it marked
    down).
    """

    def __init__(self, make_argv: Callable[[int, int], List[str]],
                 ports: Sequence[int], host: str = "127.0.0.1",
                 env: Optional[Dict[str, str]] = None,
                 fault_env: Optional[Dict[int, Dict[str, str]]] = None,
                 log_dir: Optional[str] = None,
                 max_restarts: int = 2, restart_backoff_s: float = 0.5,
                 metrics_registry=None):
        self.make_argv = make_argv
        self.host = host
        # abandoned slots were previously ONLY a log line — invisible to
        # anything that doesn't tail logs.  They land in a counter on the
        # given registry (the router's, when serve_fleet wires it) or the
        # process-global telemetry REGISTRY, and the router additionally
        # surfaces per-slot abandoned state on GET /v1/fleet/replicas
        self.metrics_registry = metrics_registry
        self.env = dict(env or os.environ)
        self.fault_env = dict(fault_env or {})   # idx -> env overlay
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="lgbm_tpu_fleet_")
        os.makedirs(self.log_dir, exist_ok=True)
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        self.replicas = [ReplicaProc(i, p) for i, p in enumerate(ports)]
        self._watch_thread = None
        self._watch_stop = None

    @property
    def urls(self) -> List[str]:
        return [f"{self.host}:{r.port}" for r in self.replicas]

    # ------------------------------------------------------------------
    def _spawn(self, rep: ReplicaProc) -> None:
        argv = self.make_argv(rep.idx, rep.port)
        env = dict(self.env)
        if rep.attempt == 0:
            env.update(self.fault_env.get(rep.idx, {}))
        else:
            # transient-fault model (cluster.py): an injected fault does
            # not recur on the relaunch
            for var in FAULT_ENV_VARS:
                env.pop(var, None)
        log_path = os.path.join(
            self.log_dir, f"replica_{rep.idx}_a{rep.attempt}.log")
        rep.log_paths.append(log_path)
        log_info(f"fleet: replica {rep.idx} (port {rep.port}, attempt "
                 f"{rep.attempt}) log: {log_path}")
        log_fh = open(log_path, "w")
        rep.proc = subprocess.Popen(argv, env=env, stdout=log_fh,
                                    stderr=subprocess.STDOUT, text=True)
        log_fh.close()                # the child keeps its own handle

    def spawn_all(self) -> None:
        for rep in self.replicas:
            if rep.proc is None:
                self._spawn(rep)

    # ------------------------------------------------------------------
    def wait_ready(self, timeout_s: float = 120.0,
                   poll_s: float = 0.25) -> None:
        """Block until every live replica answers /healthz (a replica
        process pays its jax import + model load + bundle deserialize
        before binding the port).  Raises on timeout or if a replica dies
        before ever becoming ready."""
        # the router's HTTP client, not a hand-rolled http.client loop:
        # one transport implementation per package (keep-alive pooling,
        # connection cleanup on error, transport-vs-HTTP error split)
        from .router import HttpReplica, ReplicaTransportError
        probes = {idx: HttpReplica(url)
                  for idx, url in enumerate(self.urls)}
        deadline = time.time() + timeout_s
        pending = set(range(len(self.replicas)))
        while pending:
            for idx in sorted(pending):
                rep = self.replicas[idx]
                if not rep.alive:
                    # a corpse the running watcher will respawn (budget
                    # permitting) is still "pending", not a failure —
                    # callers waiting out a restart rely on the timeout;
                    # without a watcher nothing will ever revive it, so
                    # fail fast with the log tail
                    if self._watch_thread is not None and not rep.gave_up:
                        continue
                    from ..cluster import _tail
                    log = rep.log_paths[-1] if rep.log_paths else "?"
                    raise RuntimeError(
                        f"fleet: replica {idx} died before ready "
                        f"(rc={rep.proc.poll() if rep.proc else None}); "
                        f"log: {log}\n--- tail ---\n{_tail(log)}")
                try:
                    status, _ = probes[idx].request("GET", "/healthz",
                                                    timeout_s=2.0)
                    if status == 200:
                        pending.discard(idx)
                except ReplicaTransportError:
                    pass
            if pending:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"fleet: replicas {sorted(pending)} not ready "
                        f"within {timeout_s:.0f}s")
                time.sleep(poll_s)

    # ------------------------------------------------------------------
    def watch(self) -> None:
        """One supervision step: respawn dead replicas whose backoff has
        elapsed and whose restart budget remains."""
        now = time.time()
        for rep in self.replicas:
            if rep.alive or rep.gave_up or rep.proc is None:
                continue
            rc = rep.proc.poll()
            if rep.next_spawn_at == 0.0:
                # first sight of this corpse: schedule the respawn
                if rep.restarts >= self.max_restarts:
                    rep.gave_up = True
                    self._count_abandoned(rep)
                    log_warning(
                        f"fleet: replica {rep.idx} died (rc={rc}) and its "
                        f"restart budget ({self.max_restarts}) is spent; "
                        f"abandoning the slot (log: {rep.log_paths[-1]})")
                    continue
                delay = self.restart_backoff_s * (2.0 ** rep.restarts)
                rep.next_spawn_at = now + delay
                log_warning(
                    f"fleet: replica {rep.idx} died (rc={rc}); relaunching "
                    f"in {delay:.1f}s (restart "
                    f"{rep.restarts + 1}/{self.max_restarts})")
            if now >= rep.next_spawn_at:
                rep.attempt += 1
                rep.restarts += 1
                rep.next_spawn_at = 0.0
                self._spawn(rep)

    def _count_abandoned(self, rep: ReplicaProc) -> None:
        try:
            from ..telemetry.registry import REGISTRY
            reg = (self.metrics_registry if self.metrics_registry is not None
                   else REGISTRY)
            reg.counter(
                "lgbm_fleet_replica_abandoned_total",
                "replica slots abandoned after their restart budget",
                replica=f"{self.host}:{rep.port}").inc()
        except Exception as exc:   # metrics must never break supervision
            log_warning(f"fleet: abandoned-slot counter failed: {exc!r}")

    @property
    def abandoned(self) -> List[int]:
        """Indices of slots whose restart budget is spent."""
        return [rep.idx for rep in self.replicas if rep.gave_up]

    def start_watching(self, interval_s: float = 0.2):
        """Run watch() on a daemon thread until stop_all()."""
        import threading
        if self._watch_thread is None:
            self._watch_stop = threading.Event()

            def _loop():
                while not self._watch_stop.wait(interval_s):
                    try:
                        self.watch()
                    except Exception as exc:   # never kill supervision
                        log_warning(f"fleet: watch step failed: {exc!r}")

            self._watch_thread = threading.Thread(
                target=_loop, name="lgbm-tpu-fleet-supervisor", daemon=True)
            self._watch_thread.start()
        return self

    # ------------------------------------------------------------------
    def add_slot(self, port: Optional[int] = None) -> int:
        """Scale-up: append one replica slot, spawn its process, return
        the new index.  The slot gets the full restart budget and the
        same make_argv; no fault env (scale-up is not a chaos event).
        The caller (fleet/placement/autoscale.py) waits for /healthz and
        registers the endpoint with the router."""
        if port is None:
            from ..cluster import find_open_ports
            port = find_open_ports(1, host=self.host)[0]
        rep = ReplicaProc(len(self.replicas), int(port))
        # append BEFORE spawn: watch() iterates self.replicas, and a
        # spawned-but-untracked process would leak if spawn raced a stop
        self.replicas.append(rep)
        self._spawn(rep)
        return rep.idx

    def retire_slot(self, idx: int) -> None:
        """Scale-down: kill slot ``idx`` and mark it given-up so watch()
        never respawns it.  The slot object stays (indices are shared
        with the router's replica list)."""
        rep = self.replicas[idx]
        rep.gave_up = True            # watch() skips given-up slots
        if rep.alive:
            rep.proc.terminate()
            try:
                rep.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait()
        log_info(f"fleet: replica slot {idx} (port {rep.port}) retired")

    # ------------------------------------------------------------------
    def kill(self, idx: int) -> None:
        """SIGKILL one replica (chaos switch for tests/benches that want
        an external kill instead of env-driven fault injection)."""
        rep = self.replicas[idx]
        if rep.alive:
            rep.proc.kill()
            rep.proc.wait()

    def stop_all(self) -> None:
        if self._watch_stop is not None:
            self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=10.0)
            self._watch_thread = None
        for rep in self.replicas:
            if rep.alive:
                rep.proc.terminate()
        deadline = time.time() + 5.0
        for rep in self.replicas:
            if rep.proc is None:
                continue
            while rep.proc.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if rep.proc.poll() is None:
                rep.proc.kill()
                rep.proc.wait()

    def __enter__(self) -> "FleetSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop_all()


def default_replica_argv(raw_params: Dict[str, str], port: int) -> List[str]:
    """Build a replica's CLI command from the fleet launcher's params:
    same param surface, forced into the single-process replica role.
    fleet_* keys are stripped (the replica must not recurse into a fleet)
    and the port is per-replica."""
    drop = {"task", "serving_port", "config"}
    argv = [sys.executable, "-m", "lightgbm_tpu", "task=serve",
            "fleet_role=replica", f"serving_port={port}"]
    for k, v in raw_params.items():
        if k in drop or k == "fleet_role" or k.startswith("fleet_"):
            continue
        argv.append(f"{k}={v}")
    return argv
