"""Gray-failure primitives: circuit breaker, latency digest, retry budget.

PR 7's router only understood binary failure — a replica is reachable or
it is not.  The production killer is the *gray* replica: alive, passing
health polls, answering every request at 20x latency.  Three primitives
turn that into something the router can act on, each deliberately
transport-free and clock-injectable so tier-1 tests drive every
transition without a single wall-clock sleep:

- ``LatencyDigest`` — a bounded ring of (timestamp, latency) samples with
  quantile reads over a sliding time window.  The window is the point:
  a drained replica stops producing samples, its digest goes stale
  (``quantile`` returns None), and the router's latency weight decays
  back to neutral — which is how a slow replica that got organically
  drained gets *re-admitted* for a probe without any explicit reset.
- ``CircuitBreaker`` — the classic closed -> open -> half-open machine
  fed by data-path outcomes (transport errors, timeouts, 5xx/429).
  ``failures`` consecutive failures open it; after ``cooldown_s`` it
  admits ``probes`` trial requests (half-open); all probes succeeding
  closes it, any probe failing re-opens it.  A bounded ``history`` of
  transitions is kept so soaks can assert the full walk
  closed -> open -> half_open -> closed actually happened.
- ``RetryBudget`` — a token bucket refilled by *request volume*, not
  time: every data-path request deposits ``ratio`` tokens (default 10%),
  every retry/hedge withdraws one.  Under a fleet-wide brownout the
  deposit rate and the failure rate scale together, so retries are
  capped at ``ratio`` amplification no matter how hard the storm blows —
  the fleet degrades to honest 503s instead of a retry storm.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

__all__ = ["CircuitBreaker", "LatencyDigest", "RetryBudget",
           "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"        # normal operation, failures counted
OPEN = "open"            # no traffic until cooldown_s elapses
HALF_OPEN = "half_open"  # limited probes decide: back to closed or open


class LatencyDigest:
    """Bounded ring of recent latencies with sliding-window quantiles.

    ``observe`` records (now, seconds); ``quantile(q)`` reads over samples
    younger than ``window_s`` and returns None when fewer than
    ``min_samples`` are live — "no recent evidence" is an explicit state
    (the router treats it as neutral weight), never a fabricated 0.
    """

    # quantile reads are cached this long: the routing hot path asks for
    # the same quantiles on every request, and a per-request sort of the
    # ring is pure recomputation of a value that moves at observation
    # cadence (bounded staleness; observe() invalidates immediately)
    _CACHE_TTL_S = 0.1

    def __init__(self, capacity: int = 256, window_s: float = 30.0,
                 min_samples: int = 5,
                 clock: Callable[[], float] = time.monotonic):
        self._cap = int(capacity)
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self._clock = clock
        self._buf: List[Tuple[float, float]] = []
        self._n = 0
        self._lock = threading.Lock()
        self._cache: dict = {}
        self._cache_t = -1e18

    def observe(self, seconds: float) -> None:
        entry = (self._clock(), float(seconds))
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(entry)
            else:
                self._buf[self._n % self._cap] = entry
            self._n += 1
            self._cache.clear()

    def quantile(self, q: float) -> Optional[float]:
        """q in [0, 1]; None when the window holds < min_samples."""
        now = self._clock()
        with self._lock:
            if now - self._cache_t < self._CACHE_TTL_S and q in self._cache:
                return self._cache[q]
            horizon = now - self.window_s
            live = [lat for (t, lat) in self._buf if t >= horizon]
        if len(live) < self.min_samples:
            out = None
        else:
            live.sort()
            out = live[min(int(len(live) * q), len(live) - 1)]
        with self._lock:
            if now - self._cache_t >= self._CACHE_TTL_S:
                self._cache.clear()
                self._cache_t = now
            self._cache[q] = out
        return out

    @property
    def count(self) -> int:
        with self._lock:
            return self._n


class CircuitBreaker:
    """Per-replica data-path breaker (closed -> open -> half-open).

    Not self-locking for state *reads* beyond the lock it takes on every
    mutation — callers may read ``state`` racily for display; routing
    decisions go through ``admits``/``try_acquire`` which are locked.
    ``failures <= 0`` disables the breaker entirely (always closed).
    """

    _MAX_HISTORY = 64

    def __init__(self, failures: int = 5, cooldown_s: float = 2.0,
                 probes: int = 2,
                 clock: Callable[[], float] = time.monotonic):
        self.failures = int(failures)
        self.cooldown_s = float(cooldown_s)
        self.probes = max(int(probes), 1)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED
        self._fail_streak = 0
        self._opened_at = 0.0
        self._probe_slots = 0      # half-open trial requests still grantable
        self._probe_ok = 0
        self.transitions = 0
        self.history: List[Tuple[float, str, str]] = []  # (t, from, to)

    @property
    def enabled(self) -> bool:
        return self.failures > 0

    def _set_state(self, state: str) -> None:
        if state == self.state:
            return
        self.history.append((self._clock(), self.state, state))
        del self.history[:-self._MAX_HISTORY]
        self.state = state
        self.transitions += 1

    def _maybe_half_open(self) -> None:
        """open -> half_open once the cooldown elapsed (lock held)."""
        if (self.state == OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._set_state(HALF_OPEN)
            self._probe_slots = self.probes
            self._probe_ok = 0

    def admits(self) -> bool:
        """Non-consuming routability check (ranking).  True in closed, in
        half-open while probe slots remain, and in open once the cooldown
        elapsed (which transitions to half-open)."""
        if not self.enabled:
            return True
        with self._lock:
            self._maybe_half_open()
            if self.state == CLOSED:
                return True
            return self.state == HALF_OPEN and self._probe_slots > 0

    def wants_probe(self) -> bool:
        """True when the breaker is half-open with grantable probe
        slots.  The router gives such a replica PROBE PRIORITY in
        ranking — a drained/slow replica never wins a cost comparison,
        so without deliberate priority its half-open probes would wait
        forever and the breaker could never close."""
        if not self.enabled:
            return False
        with self._lock:
            self._maybe_half_open()
            return self.state == HALF_OPEN and self._probe_slots > 0

    # try_acquire grant kinds (both truthy; 0/False = denied)
    GRANT_NORMAL = 1
    GRANT_PROBE = 2

    def try_acquire(self) -> int:
        """Consume permission for ONE attempt.  Unlimited in closed
        (returns GRANT_NORMAL); half-open grants at most ``probes``
        concurrent trials (returns GRANT_PROBE — the caller passes
        ``probe=True`` back with the outcome, so only REAL probes can
        close the breaker); open grants nothing (returns 0)."""
        if not self.enabled:
            return self.GRANT_NORMAL
        with self._lock:
            self._maybe_half_open()
            if self.state == CLOSED:
                return self.GRANT_NORMAL
            if self.state == HALF_OPEN and self._probe_slots > 0:
                self._probe_slots -= 1
                return self.GRANT_PROBE
            return 0

    def record_success(self, probe: bool = True) -> None:
        """``probe`` is the flag threaded from try_acquire (GRANT_PROBE):
        in half-open, only outcomes of attempts that actually consumed a
        probe slot may count toward closing — a slow success ISSUED
        BEFORE the breaker opened (the gray replica's in-flight backlog,
        still completing through the cooldown) is pre-outage evidence
        and must not re-admit a replica no probe ever re-tested."""
        if not self.enabled:
            return
        with self._lock:
            if self.state == HALF_OPEN:
                if not probe:
                    return   # stale (pre-open) evidence: ignore
                self._probe_ok += 1
                if self._probe_ok >= self.probes:
                    self._set_state(CLOSED)
                    self._fail_streak = 0
                else:
                    # slots are a CONCURRENCY throttle, not a lifetime
                    # grant: a completed probe hands its slot back so
                    # the machine can keep probing toward `probes`
                    # successes instead of deadlocking half-open
                    self._probe_slots = min(self._probe_slots + 1,
                                            self.probes)
            elif self.state == CLOSED:
                self._fail_streak = 0

    def record_failure(self, probe: bool = True) -> None:
        if not self.enabled:
            return
        with self._lock:
            if self.state == HALF_OPEN:
                if not probe:
                    return   # stale (pre-open) evidence: ignore
                # one failed probe is proof enough: back to open
                self._set_state(OPEN)
                self._opened_at = self._clock()
                self._fail_streak = 0
                return
            if self.state == CLOSED:
                self._fail_streak += 1
                if self._fail_streak >= self.failures:
                    self._set_state(OPEN)
                    self._opened_at = self._clock()

    def record_neutral(self, probe: bool = True) -> None:
        """An attempt whose outcome says nothing about the replica's
        health (deadline-squeezed timeout, 429/504 admission verdicts):
        in half-open it releases the probe slot the attempt consumed —
        without this, neutral outcomes leak slots and the breaker can
        deadlock half-open with no probes left to grant."""
        if not self.enabled:
            return
        with self._lock:
            if self.state == HALF_OPEN and probe:
                self._probe_slots = min(self._probe_slots + 1,
                                        self.probes)

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state, "transitions": self.transitions,
                    "fail_streak": self._fail_streak}


class RetryBudget:
    """Volume-coupled token bucket shared by reroutes and hedges.

    ``deposit()`` is called once per data-path request and adds ``ratio``
    tokens (capped at ``cap``); ``try_spend()`` withdraws one token per
    retry/hedge.  ``initial`` seeds the bucket so an isolated failure on
    a quiet fleet can still reroute (a cold bucket would turn the very
    first replica death into a failed request).  ``ratio <= 0`` disables
    the budget (every spend granted) — the pre-hardening behavior.
    """

    def __init__(self, ratio: float = 0.1, cap: float = 100.0,
                 initial: float = 10.0):
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._tokens = min(float(initial), self.cap)
        self._lock = threading.Lock()
        self.spent = 0
        self.denied = 0

    @property
    def enabled(self) -> bool:
        return self.ratio > 0

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def deposit(self, n: float = 1.0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._tokens = min(self._tokens + self.ratio * float(n),
                               self.cap)

    def try_spend(self) -> bool:
        if not self.enabled:
            return True
        with self._lock:
            # epsilon: ten 10% deposits must grant one retry — summing
            # 0.1 ten times lands a hair under 1.0 in binary floats
            if self._tokens >= 1.0 - 1e-9:
                self._tokens -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False

    def refund(self) -> None:
        """Return one token (a spend whose action was never taken — e.g.
        a hedge token granted but the shared retry budget then denied)."""
        if not self.enabled:
            return
        with self._lock:
            self._tokens = min(self._tokens + 1.0, self.cap)
            self.spent = max(self.spent - 1, 0)
