"""SLO-aware backpressure: the router-side breach→shed→recover machine.

PR 1's only backpressure was ``QueueFullError`` — a replica had to be
LITERALLY full before anyone reacted, by which point its latency tail was
already blown.  Fleet routing instead watches each replica's telemetry
gauges (p99 latency, queued rows, in-flight batch fill — the replica
exposes them on ``GET /v1/fleet/health``) against explicit SLO targets
and reacts BEFORE the queue-full cliff:

- a replica whose gauges breach the targets for ``breach_polls``
  CONSECUTIVE polls is marked ``shed``: the router stops routing new load
  to it (reroute to healthy peers) until it has been back under target
  for ``recover_polls`` consecutive polls — hysteresis on both edges so a
  single noisy poll neither sheds a healthy replica nor restores a sick
  one;
- a replica whose health poll fails outright (connection refused, timed
  out — the killed-replica case) is ``down`` immediately, no hysteresis:
  there is nothing to be gentle with, and every poll it misses would be a
  routed request lost;
- when NO replica is routable the router itself sheds (HTTP 503) — load
  the fleet cannot serve within SLO is rejected at the front door where
  the client can back off, instead of queueing into a latency collapse.

The machine is deliberately transport-free — ``observe`` takes a plain
gauges dict (or None for an unreachable replica), so tier-1 tests drive
every transition with injected values and no sockets.
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["SLOPolicy", "ReplicaSLO", "HEALTHY", "SHED", "DOWN",
           "full_forest_affordable"]

HEALTHY = "healthy"   # routable
SHED = "shed"         # reachable but over SLO: no new load until recovered
DOWN = "down"         # unreachable: no new load until it polls ok again


def full_forest_affordable(remaining_s: float, p99_ms: float,
                           safety: float = 1.0) -> bool:
    """Can a request with ``remaining_s`` of deadline budget afford a
    FULL-forest predict, given the model's recent p99 evidence?

    The early-exit cascade's deadline mode (router cascade_mode=deadline)
    serves the calibrated prefix answer with ``degraded=true`` when this
    says no — converting a would-be 504 into a useful response.  With no
    latency evidence yet (p99 <= 0: cold model, idle window) the answer
    is True: degradation must be evidence-driven, never the default.
    ``safety`` scales the required headroom (>1 degrades earlier)."""
    if p99_ms <= 0:
        return True
    return float(remaining_s) * 1e3 >= float(p99_ms) * float(safety)


class SLOPolicy:
    """SLO targets plus the hysteresis widths.

    A target of 0 (or negative) disables that gauge's check, so a
    deployment can shed on queue depth alone, p99 alone, or both.
    """

    def __init__(self, p99_ms: float = 0.0, queue_rows: int = 0,
                 breach_polls: int = 3, recover_polls: int = 5):
        self.p99_ms = float(p99_ms)
        self.queue_rows = int(queue_rows)
        self.breach_polls = max(int(breach_polls), 1)
        self.recover_polls = max(int(recover_polls), 1)

    def breaches(self, gauges: Dict) -> List[str]:
        """Which targets this gauge snapshot violates (empty = within SLO)."""
        out = []
        if self.p99_ms > 0 and float(gauges.get("p99_ms", 0.0)) > self.p99_ms:
            out.append(f"p99_ms {float(gauges['p99_ms']):.1f} > "
                       f"{self.p99_ms:g}")
        if (self.queue_rows > 0
                and int(gauges.get("queue_rows", 0)) > self.queue_rows):
            out.append(f"queue_rows {int(gauges['queue_rows'])} > "
                       f"{self.queue_rows}")
        return out


class ReplicaSLO:
    """One replica's breach→shed→recover state, fed by health polls.

    Not self-locking: the router mutates it only under its own lock (one
    poll loop, plus ``mark_down`` from forwarding threads).
    """

    def __init__(self, policy: Optional[SLOPolicy] = None):
        self.policy = policy or SLOPolicy()
        self.state = HEALTHY          # optimistic before the first poll
        self.last_gauges: Optional[Dict] = None
        self.last_reasons: List[str] = []
        self._breach_streak = 0
        self._ok_streak = 0
        self._last_requests: Optional[int] = None
        self.transitions = 0          # state changes ever (observability)

    @property
    def routable(self) -> bool:
        return self.state == HEALTHY

    def _set_state(self, state: str) -> None:
        if state != self.state:
            self.state = state
            self.transitions += 1

    def mark_down(self, reason: str = "transport error") -> None:
        """Immediate demotion on a forwarding failure: the next request
        must not wait for the poll loop to notice a dead replica."""
        self.last_reasons = [reason]
        self._breach_streak = 0
        self._ok_streak = 0
        self._set_state(DOWN)

    def observe(self, gauges: Optional[Dict]) -> str:
        """Feed one health poll (None = unreachable); returns the state."""
        self.last_gauges = gauges
        if gauges is None:
            self.mark_down("health poll failed")
            self._last_requests = None
            return self.state
        reasons = self.policy.breaches(gauges)
        # staleness guard: the replica's p99 gauge is a ring of PAST
        # request latencies — once shed, the replica gets no traffic, the
        # ring never refreshes, and a p99 breach would hold forever (a
        # permanent shed, fleet-wide 503 if correlated).  A poll that saw
        # no new requests and an empty queue cannot RE-prove a latency
        # breach, so drop the p99 reason and let the recovery hysteresis
        # run; if the replica is still slow, real traffic re-sheds it
        # after breach_polls — bounded probing instead of a death spiral.
        requests = gauges.get("requests")
        idle = (requests is not None and requests == self._last_requests
                and int(gauges.get("queue_rows", 0)) == 0
                and int(gauges.get("inflight_rows", 0)) == 0)
        if idle:
            reasons = [r for r in reasons if not r.startswith("p99_ms")]
        self._last_requests = requests
        self.last_reasons = reasons
        if reasons:
            self._ok_streak = 0
            self._breach_streak += 1
            if self.state == DOWN:
                # reachable again but over target: straight to shed — a
                # restarted replica drowning in backlog is not routable
                self._set_state(SHED)
            elif (self.state == HEALTHY
                    and self._breach_streak >= self.policy.breach_polls):
                self._set_state(SHED)
        else:
            self._breach_streak = 0
            self._ok_streak += 1
            if self.state == DOWN:
                # back from the dead: hold in shed until it proves itself
                # for recover_polls like any other recovering replica
                self._set_state(SHED)
            if (self.state == SHED
                    and self._ok_streak >= self.policy.recover_polls):
                self._set_state(HEALTHY)
        return self.state
