"""FleetRouter: one front door for N replica workers.

The router is the fleet's only stateful coordination point, and it holds
no model state at all — replicas own the models (each a full ServingApp
warmed from the shared AOT bundle), the router owns *placement*:

- **routing**: each predict goes to the routable replica with the fewest
  queued+in-flight rows as of its last health poll (cheapest useful load
  signal; ties break round-robin so equally-idle replicas share warmup
  traffic);
- **rerouting**: a forwarding failure (connection refused/reset — the
  killed-replica case) marks the replica down IMMEDIATELY and retries the
  request on the next-best peer, so one replica dying mid-soak loses zero
  requests; a replica's own 429 (its bounded queue overflowed between
  polls) is treated the same way — the load reroutes instead of
  surfacing a retryable error to the client;
- **shedding**: when no replica is routable (all breached/down per
  fleet/slo.py) the router answers 503 at the front door — SLO-aware
  backpressure instead of the old queue-full-only cliff;
- **broadcast**: publish/rollback fan out to EVERY reachable replica so a
  hot-swap lands fleet-wide in one call.

``FleetRouter.handle(method, path, body)`` keeps the same transport-free
contract as ``ServingApp.handle`` — ``serving.server.make_server`` wraps
either, tests drive the router without sockets by injecting fake replica
endpoints, and the router's own gauges (per-replica state/load, forwards,
reroutes, sheds, router-side latency) live in a telemetry
``MetricsRegistry`` rendered at ``GET /v1/metrics/prometheus``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..log import LightGBMError, log_info, log_warning
from ..serving.metrics import LatencyWindow
from ..telemetry.registry import MetricsRegistry
from .slo import ReplicaSLO, SLOPolicy

__all__ = ["FleetRouter", "HttpReplica", "ReplicaTransportError"]


class ReplicaTransportError(LightGBMError):
    """The replica could not be reached at all (vs. an HTTP error it
    returned): the router may safely retry elsewhere."""


class HttpReplica:
    """Minimal stdlib HTTP client for one replica endpoint.

    Connections are pooled per (thread, replica) — keep-alive matters at
    soak rates, where a fresh TCP connect per forwarded predict is real
    overhead.  Any socket-level failure drops the pooled connection and
    surfaces as ``ReplicaTransportError`` so the router can distinguish
    "replica gone" (retry elsewhere) from "replica answered an error"
    (forward it); a restarted replica just gets a fresh connection on the
    next call."""

    def __init__(self, url: str, timeout_s: float = 30.0):
        # accept "host:port" or "http://host:port"
        url = url.strip()
        if url.startswith("http://"):
            url = url[len("http://"):]
        url = url.rstrip("/")
        if ":" not in url:
            raise LightGBMError(f"replica url needs host:port, got {url!r}")
        host, port = url.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.name = f"{self.host}:{self.port}"
        self.timeout_s = float(timeout_s)
        self._local = threading.local()
        # bumped via invalidate_pool() when the router learns the replica
        # died or restarted: pooled keep-alive sockets from before then
        # are stale, and a non-retried POST (publish/rollback — retrying
        # could double-apply) written to one fails with a broken pipe
        # even though the replica is back and healthy
        self._gen = 0

    def invalidate_pool(self) -> None:
        """Presume every pooled connection stale; reconnect on next use."""
        self._gen += 1

    def _conn(self, timeout_s: float):
        import http.client
        import socket
        conn = getattr(self._local, "conn", None)
        if conn is not None and getattr(self._local, "gen", -1) != self._gen:
            self._drop_conn()
            conn = None
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=timeout_s)
            conn.connect()
            # TCP_NODELAY: a forwarded predict is one small write per
            # direction — Nagle + delayed ACK otherwise turns each hop
            # into tens of ms of idle waiting
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.conn = conn
            self._local.gen = self._gen
        else:
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:
                pass

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                timeout_s: Optional[float] = None) -> Tuple[int, dict]:
        import http.client
        payload = None if body is None else json.dumps(body).encode()
        headers = ({"Content-Type": "application/json"}
                   if payload is not None else {})
        # one retry on a fresh connection: a pooled keep-alive socket the
        # server closed between calls fails with a reset/EOF that says
        # nothing about the replica's health; a FRESH connect failing is
        # the replica genuinely unreachable — no retry.  Only requests
        # that are safe to EXECUTE TWICE auto-retry: a publish/rollback
        # the replica may have already processed before the socket died
        # would double-apply (two version bumps — a later rollback then
        # lands on the duplicate); predicts are pure per-row functions.
        retry_safe = method == "GET" or path.endswith(":predict")
        for attempt in (0, 1):
            reused = getattr(self._local, "conn", None) is not None
            try:
                conn = self._conn(timeout_s or self.timeout_s)
                conn.request(method, path, payload, headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.will_close:
                    self._drop_conn()
                try:
                    return resp.status, json.loads(data)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    # e.g. the Prometheus text route
                    return resp.status, {"text": data.decode(errors="replace")}
            except (OSError, http.client.HTTPException) as exc:
                self._drop_conn()
                if not reused or attempt == 1 or not retry_safe:
                    raise ReplicaTransportError(
                        f"replica {self.name}: {type(exc).__name__}: "
                        f"{exc}") from exc

    def health(self, timeout_s: float = 2.0) -> Optional[Dict]:
        """The replica's SLO gauges, or None when unreachable/unhealthy."""
        try:
            status, body = self.request("GET", "/v1/fleet/health",
                                        timeout_s=timeout_s)
        except ReplicaTransportError:
            return None
        if status != 200:
            return None
        return body.get("gauges", {})


class _Replica:
    """Router-side record: endpoint + SLO state + last-known load."""

    def __init__(self, endpoint, slo: ReplicaSLO):
        self.endpoint = endpoint
        self.slo = slo
        self.load_rows = 0        # queued + in-flight rows at last poll
        # rows forwarded by THIS router and not yet answered: the live
        # complement to load_rows, which refreshes only at poll time —
        # without it every request between two polls ranks the same
        # replica first and herds onto it for a full poll interval
        self.router_inflight_rows = 0
        self.last_poll_s = 0.0
        # restart evidence gating publish replay, so a transient
        # health-poll blip doesn't trigger a redundant publish that
        # desynchronizes version counters fleet-wide.  Primary signal:
        # the replica's boot_s gauge (a restarted replica is a fresh
        # process with a new boot time — works even before it serves
        # its first request).  Fallback for gauge sources without
        # boot_s: a rejoining replica reporting FEWER cumulative
        # requests than this high-water mark was genuinely restarted.
        self.boot_s: Optional[float] = None
        self.requests_high = 0


class FleetRouter:
    def __init__(self, replicas: List, policy: Optional[SLOPolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 poll_interval_ms: float = 100.0,
                 request_timeout_s: float = 30.0,
                 health_timeout_s: float = 2.0,
                 autostart: bool = True):
        if not replicas:
            raise LightGBMError("FleetRouter needs at least one replica")
        policy = policy or SLOPolicy()
        self._replicas = [_Replica(ep, ReplicaSLO(policy))
                          for ep in replicas]
        self.policy = policy
        self.registry = registry or MetricsRegistry()
        self.poll_interval_s = float(poll_interval_ms) / 1e3
        self.request_timeout_s = float(request_timeout_s)
        self.health_timeout_s = float(health_timeout_s)
        self._lock = threading.Lock()
        self._rr = 0                      # round-robin tie-breaker
        self._next_demand_poll_s = 0.0    # rate limit for pollless mode
        self._started = False
        self._closed = False
        # last successful publish body per model name: replayed to a
        # replica that comes back from DOWN, because a supervised restart
        # respawns it from its ORIGINAL argv — without the replay it
        # would rejoin serving the pre-hot-swap model indefinitely
        self._published: Dict[str, dict] = {}
        from concurrent.futures import ThreadPoolExecutor
        # SEPARATE pools for health sweeps and publish broadcasts: a
        # publish occupies a worker for up to request_timeout_s per
        # replica (model load + warmup), and health probes queued behind
        # broadcasts would time out and flap perfectly healthy replicas
        # down fleet-wide — no shared sizing is safe against two
        # overlapping broadcasts, so the sweep gets its own workers
        self._health_pool = ThreadPoolExecutor(
            max_workers=max(len(replicas), 2),
            thread_name_prefix="lgbm-tpu-fleet-health")
        self._bcast_pool = ThreadPoolExecutor(
            max_workers=max(len(replicas), 2),
            thread_name_prefix="lgbm-tpu-fleet-bcast")
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()
        self.latency = LatencyWindow()
        # router-side observables, labeled per replica where meaningful
        reg = self.registry
        self._m_requests = reg.counter(
            "lgbm_fleet_requests_total", "predict requests at the router")
        self._m_shed = reg.counter(
            "lgbm_fleet_shed_total",
            "requests shed because no replica was within SLO")
        self._m_reroutes = reg.counter(
            "lgbm_fleet_reroutes_total",
            "forwards retried on another replica after a failure")
        self._m_errors = reg.counter(
            "lgbm_fleet_errors_total",
            "requests that failed on every routable replica")
        self._m_publish_partial = reg.counter(
            "lgbm_fleet_publish_partial_total",
            "publish broadcasts that landed on only a subset of replicas "
            "and were rolled back to keep the fleet single-version")
        self._m_latency = reg.histogram(
            "lgbm_fleet_request_latency_seconds",
            "router-side end-to-end predict latency")
        self._m_forwarded = [reg.counter(
            "lgbm_fleet_forwarded_total", "predicts forwarded",
            replica=r.endpoint.name) for r in self._replicas]
        self._m_up = [reg.gauge(
            "lgbm_fleet_replica_up",
            "1 routable / 0 shed or down", replica=r.endpoint.name)
            for r in self._replicas]
        self._m_load = [reg.gauge(
            "lgbm_fleet_replica_load_rows",
            "queued+in-flight rows at last poll",
            replica=r.endpoint.name) for r in self._replicas]
        self._m_p99 = [reg.gauge(
            "lgbm_fleet_replica_p99_ms", "replica p99 at last poll",
            replica=r.endpoint.name) for r in self._replicas]
        self._m_fill = [reg.gauge(
            "lgbm_fleet_replica_batch_fill",
            "replica in-flight batch fill at last poll",
            replica=r.endpoint.name) for r in self._replicas]
        for g in self._m_up:
            g.set(1)                       # optimistic, like ReplicaSLO
        if autostart:
            self.start()

    # ------------------------------------------------------------------
    def start(self) -> "FleetRouter":
        self._started = True
        if self._poll_thread is None and self.poll_interval_s > 0:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="lgbm-tpu-fleet-poll",
                daemon=True)
            self._poll_thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10.0)
            self._poll_thread = None
        self._health_pool.shutdown(wait=False)
        self._bcast_pool.shutdown(wait=False)

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    def poll_once(self) -> None:
        """One health sweep: refresh every replica's SLO state + gauges.
        Public so tests (and a pollless router) can drive it directly.

        Health requests go out IN PARALLEL (persistent worker pool, so
        the per-thread connection pooling still applies): each can block
        up to health_timeout_s, and one hung replica must not stretch
        every other replica's detection/recovery hysteresis by its
        timeout."""
        futures = [self._health_pool.submit(rep.endpoint.health,
                                            self.health_timeout_s)
                   for rep in self._replicas]
        for i, rep in enumerate(self._replicas):
            try:
                gauges = futures[i].result(self.health_timeout_s + 5.0)
            except Exception:
                gauges = None
            with self._lock:
                before = rep.slo.state
                state = rep.slo.observe(gauges)
                rep.last_poll_s = time.time()
                requests = (int(gauges.get("requests", 0))
                            if gauges is not None else 0)
                # replay only on evidence of a real restart (a transient
                # poll blip must not trigger a redundant publish):
                # boot_s changed when available — never-seen counts as
                # changed, a down replica we know nothing about may have
                # missed a publish — else the requests-drop heuristic
                if gauges is not None and "boot_s" in gauges:
                    restarted = gauges["boot_s"] != rep.boot_s
                else:
                    restarted = requests < rep.requests_high
                replay = (before == "down" and gauges is not None
                          and bool(self._published) and restarted)
                published = dict(self._published) if replay else None
                if gauges is None or restarted:
                    # every pooled keep-alive socket predating a death /
                    # restart is stale; reconnect lazily (publishes are
                    # not retried on stale sockets — see HttpReplica)
                    invalidate = getattr(rep.endpoint, "invalidate_pool",
                                         None)
                    if invalidate is not None:
                        invalidate()
                if gauges is not None:
                    rep.boot_s = gauges.get("boot_s", rep.boot_s)
                    if replay:
                        rep.requests_high = requests
                    else:
                        rep.requests_high = max(rep.requests_high,
                                                requests)
                    rep.load_rows = (int(gauges.get("queue_rows", 0))
                                     + int(gauges.get("inflight_rows", 0)))
                    self._m_load[i].set(rep.load_rows)
                    self._m_p99[i].set(float(gauges.get("p99_ms", 0.0)))
                    self._m_fill[i].set(float(gauges.get("batch_fill", 0.0)))
                self._m_up[i].set(1 if rep.slo.routable else 0)
            if replay:
                # back from the dead: a supervised restart reloaded the
                # replica's ORIGINAL models, so hot-swaps it missed must
                # be replayed before it takes real traffic (it is still
                # in shed for recover_polls polls — the replay usually
                # wins that race, and a lost race only serves the old
                # version briefly, same as before the swap landed)
                threading.Thread(target=self._replay_publishes,
                                 args=(rep, published), daemon=True,
                                 name="lgbm-tpu-fleet-replay").start()
            if state != before:
                (log_warning if state != "healthy" else log_info)(
                    f"fleet: replica {rep.endpoint.name} {before} -> "
                    f"{state} ({'; '.join(rep.slo.last_reasons) or 'ok'})")

    def _replay_publishes(self, rep, published: Dict[str, dict]) -> None:
        for name in published:
            # re-read the cache at send time, and re-send if a concurrent
            # fleet-wide publish moved it while our replay was in flight —
            # otherwise the replay could land AFTER a newer broadcast and
            # pin this one replica on the older version until its next
            # restart.  Bounded: a live system converges in one pass.
            for _ in range(3):
                with self._lock:
                    body = self._published.get(name)
                if body is None:          # rolled back meanwhile
                    break
                try:
                    status, _ = rep.endpoint.request(
                        "POST", f"/v1/models/{name}:publish", body,
                        timeout_s=self.request_timeout_s)
                    (log_info if status == 200 else log_warning)(
                        f"fleet: replayed publish of {name!r} to rejoined "
                        f"replica {rep.endpoint.name} (status {status})")
                except ReplicaTransportError as exc:
                    log_warning(f"fleet: publish replay of {name!r} to "
                                f"{rep.endpoint.name} failed: {exc}")
                    break
                with self._lock:
                    if self._published.get(name) == body:
                        break             # cache unchanged: we sent latest

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as exc:     # a poll bug must not kill routing
                log_warning(f"fleet: health poll failed: {exc!r}")

    # ------------------------------------------------------------------
    _DEMAND_POLL_MIN_INTERVAL_S = 1.0

    def _maybe_poll_inline(self) -> None:
        """fleet_poll_ms=0 runs no poll thread, so health state refreshes
        ON DEMAND here instead: recovery (down -> shed -> healthy) only
        happens inside ReplicaSLO.observe, which only poll_once calls —
        without this, one transport blip would shed a replica forever.
        Rate-limited so a down replica costs at most one health sweep per
        interval, not one per request.  Only active on a STARTED router:
        an unstarted one (autostart=False, tests) is under manual
        poll_once control."""
        if (not self._started or self._poll_thread is not None
                or self._closed):
            return
        now = time.time()
        with self._lock:
            need = any(not rep.slo.routable or rep.last_poll_s == 0.0
                       for rep in self._replicas)
            if not need or now < self._next_demand_poll_s:
                return
            self._next_demand_poll_s = now + self._DEMAND_POLL_MIN_INTERVAL_S
        self.poll_once()

    def _ranked(self) -> List[int]:
        """Routable replica indices, least-loaded first (round-robin among
        equals so idle replicas share traffic).  Load is the replica's
        last-polled queue+in-flight rows PLUS rows this router has
        forwarded since and not yet heard back about — the live term is
        what spreads concurrent requests between polls."""
        self._maybe_poll_inline()
        with self._lock:
            self._rr += 1
            order = [(rep.load_rows + rep.router_inflight_rows,
                      (i + self._rr) % len(self._replicas), i)
                     for i, rep in enumerate(self._replicas)
                     if rep.slo.routable]
        return [i for _, _, i in sorted(order)]

    def _mark_down(self, idx: int, reason: str) -> None:
        rep = self._replicas[idx]
        with self._lock:
            rep.slo.mark_down(reason)
            self._m_up[idx].set(0)
        invalidate = getattr(rep.endpoint, "invalidate_pool", None)
        if invalidate is not None:
            invalidate()
        log_warning(f"fleet: replica {rep.endpoint.name} marked down "
                    f"({reason})")

    def _forward_predict(self, name: str, body: dict) -> Tuple[int, dict]:
        self._m_requests.inc()
        t0 = time.perf_counter()
        rows = body.get("rows")
        # a flat 1-D body is ONE row of n_features (ServingApp reshapes
        # it), not n_features rows — miscounting it would make the
        # serving replica look features-times busier than it is
        nrows = (len(rows) if isinstance(rows, list) and rows
                 and isinstance(rows[0], (list, tuple)) else 1)
        attempts = 0
        candidates = self._ranked()
        tried = set()
        last_err: Optional[str] = None
        while candidates:
            idx = candidates[0]
            tried.add(idx)
            rep = self._replicas[idx]
            attempts += 1
            with self._lock:
                rep.router_inflight_rows += nrows
            try:
                status, payload = rep.endpoint.request(
                    "POST", f"/v1/models/{name}:predict", body,
                    timeout_s=self.request_timeout_s)
            except ReplicaTransportError as exc:
                self._mark_down(idx, str(exc))
                last_err = str(exc)
                self._m_reroutes.inc()
                candidates = [i for i in self._ranked() if i not in tried]
                continue
            finally:
                with self._lock:
                    rep.router_inflight_rows -= nrows
            if status == 429 or status >= 500:
                # 429: the replica's own bounded queue overflowed between
                # polls; 5xx: it is draining for shutdown/restart — both
                # are load to reroute, not errors to forward
                last_err = payload.get("error", f"replica status {status}")
                self._m_reroutes.inc()
                candidates = [i for i in self._ranked() if i not in tried]
                continue
            elapsed = time.perf_counter() - t0
            self.latency.observe(elapsed)
            self._m_latency.observe(elapsed)
            self._m_forwarded[idx].inc()
            if isinstance(payload, dict):
                payload.setdefault("replica", rep.endpoint.name)
                if attempts > 1:
                    payload.setdefault("rerouted", attempts - 1)
            return status, payload
        if last_err is None:
            # nothing was routable to begin with: SLO shedding
            self._m_shed.inc()
            states = self.replica_states()
            return 503, {"error": "fleet shedding load: no replica within "
                                  "SLO", "replicas": states}
        self._m_errors.inc()
        return 503, {"error": f"no replica could serve the request; "
                              f"last: {last_err}"}

    def _broadcast(self, method: str, path: str, body: dict,
                   name: str, verb: str) -> Tuple[int, dict]:
        """publish/rollback fan-out: try every replica (even shed ones —
        a recovering replica must not come back serving a stale model),
        IN PARALLEL — a publish pays model load + bundle deserialize +
        warmup per replica, and a fleet-wide hot-swap should cost one
        replica's worth of wall clock, not N.  Succeeds if every
        REACHABLE replica succeeded.  A PARTIAL publish (some 200s, some
        refusals) rolls the successes back — the fleet must never
        silently serve mixed versions — and bumps
        ``lgbm_fleet_publish_partial_total``."""
        def _one(rep):
            try:
                status, payload = rep.endpoint.request(
                    method, path, body, timeout_s=self.request_timeout_s)
                return {"status": status, **(
                    payload if isinstance(payload, dict) else {})}
            except ReplicaTransportError as exc:
                # a socket TIMEOUT is not "unreachable": the replica is
                # alive (health polls keep passing, so it never restarts
                # and the rejoin replay never fires) and the publish may
                # still land after we stop waiting — an UNKNOWN outcome
                # that must fail the broadcast like the pool-level
                # timeout below, not be excluded from the success
                # computation.  Only a refused/reset connection (replica
                # genuinely gone; it republishes from its argv or the
                # replay cache on rejoin) is safe to exclude.
                if isinstance(exc.__cause__, TimeoutError):
                    return {"status": -1,
                            "error": f"publish outcome unknown: {exc}"}
                return {"status": 0, "error": str(exc)}

        # the persistent broadcast pool, not ad-hoc threads: its workers'
        # thread-local connections get reused across broadcasts instead
        # of leaking one fresh socket per replica per publish (and it is
        # NOT the health pool — see __init__ on starvation)
        futures = [self._bcast_pool.submit(_one, rep)
                   for rep in self._replicas]
        results: Dict[str, Dict] = {}
        for rep, fut in zip(self._replicas, futures):
            try:
                results[rep.endpoint.name] = fut.result(
                    self.request_timeout_s + 5.0)
            except Exception:
                # a publish that outlived its timeout has an UNKNOWN
                # outcome — that must fail the broadcast, not be
                # silently excluded from the success computation
                results[rep.endpoint.name] = {
                    "status": -1,
                    "error": "publish still in flight (timed out)"}
        ok = sum(r["status"] == 200 for r in results.values())
        reachable = [r for r in results.values() if r["status"] != 0]
        all_ok = bool(reachable) and all(r["status"] == 200
                                         for r in reachable)
        if verb == "publish" and not all_ok and ok > 0:
            # PARTIAL publish: some replicas installed the new version,
            # others refused (or their outcome is unknown).  Leaving it be
            # would silently serve MIXED versions behind one front door —
            # the worst failure mode, because every response looks
            # healthy.  Roll the confirmed successes back so the fleet
            # converges on the old version; replicas with UNKNOWN
            # outcomes (status -1 timeouts) are deliberately NOT rolled
            # back — a rollback on a replica whose publish never landed
            # would withdraw its previous GOOD version instead.
            self._m_publish_partial.inc()
            base_path = path[:path.rfind(":")]
            to_undo = [rep for rep in self._replicas
                       if results[rep.endpoint.name]["status"] == 200]
            log_warning(
                f"fleet: partial publish of {name!r} ({ok}/"
                f"{len(self._replicas)} replicas) — rolling back the "
                f"{len(to_undo)} that succeeded")

            def _undo(rep):
                # a replica whose FIRST version of this model just
                # landed (publish returned version 1) has no previous to
                # roll back to — its undo is :unpublish, restoring the
                # nothing-published state the refusing replicas are in
                first = results[rep.endpoint.name].get("version") == 1
                undo_path = base_path + (":unpublish" if first
                                         else ":rollback")
                try:
                    status, _ = rep.endpoint.request(
                        "POST", undo_path, None,
                        timeout_s=self.request_timeout_s)
                    return status
                except ReplicaTransportError as exc:
                    log_warning(f"fleet: rollback of partial publish on "
                                f"{rep.endpoint.name} failed: {exc}")
                    return 0
            undo_futs = [self._bcast_pool.submit(_undo, rep)
                         for rep in to_undo]
            for rep, fut in zip(to_undo, undo_futs):
                try:
                    status = fut.result(self.request_timeout_s + 5.0)
                except Exception:
                    status = 0
                results[rep.endpoint.name]["rolled_back"] = status == 200
                if status != 200:
                    # still mixed: say so loudly — the operator's signal
                    # is the partial counter plus this per-replica flag
                    log_warning(
                        f"fleet: replica {rep.endpoint.name} may still "
                        f"serve the withdrawn version of {name!r} "
                        f"(rollback status {status})")
        if all_ok:
            # maintain the rejoin-replay cache: a fleet-wide publish is
            # remembered (replayed to replicas that restart with their
            # original models), and a fleet-wide ROLLBACK withdraws the
            # memory — replaying a rolled-back publish to a rejoining
            # replica would resurrect the withdrawn version on one
            # replica only
            if verb == "publish":
                with self._lock:
                    self._published[name] = dict(body)
            elif verb == "rollback":
                with self._lock:
                    self._published.pop(name, None)
        return (200 if all_ok else 502), {"replicas": results,
                                          "succeeded": ok}

    # ------------------------------------------------------------------
    def replica_states(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                rep.endpoint.name: {
                    "state": rep.slo.state,
                    "load_rows": rep.load_rows,
                    "reasons": list(rep.slo.last_reasons),
                    "transitions": rep.slo.transitions,
                }
                for rep in self._replicas
            }

    def handle(self, method: str, path: str,
               body: Optional[dict] = None) -> Tuple[int, dict]:
        """Transport-free request handler, ServingApp.handle-compatible."""
        try:
            return self._route(method.upper(), path.rstrip("/") or "/",
                               body or {})
        except ReplicaTransportError as exc:
            return 502, {"error": str(exc)}
        except LightGBMError as exc:
            return 400, {"error": str(exc)}
        except (KeyError, ValueError, TypeError) as exc:
            return 400, {"error": f"{type(exc).__name__}: {exc}"}
        except Exception as exc:
            # same contract as ServingApp.handle: an escaped exception
            # tears the connection down, which an upstream load balancer
            # cannot distinguish from a dead router — always answer
            log_warning(f"fleet: unhandled router error for {method} "
                        f"{path}: {exc!r}")
            return 500, {"error": f"internal: {type(exc).__name__}: {exc}"}

    def _route(self, method: str, path: str, body: dict) -> Tuple[int, dict]:
        if self._closed:
            return 503, {"error": "router is closed"}
        if method == "GET" and path == "/healthz":
            states = self.replica_states()
            routable = sum(s["state"] == "healthy" for s in states.values())
            return 200, {"status": "ok" if routable else "shedding",
                         "role": "router", "routable": routable,
                         "replicas": states}
        if method == "GET" and path == "/v1/fleet/replicas":
            return 200, {"replicas": self.replica_states()}
        if method == "GET" and path == "/v1/metrics":
            out = {"router": self.registry.snapshot(),
                   "replicas": self.replica_states()}
            out["router"]["p_ms"] = self.latency.percentiles()
            return 200, out
        if method == "GET" and path == "/v1/metrics/prometheus":
            from ..telemetry import prometheus_text
            return 200, prometheus_text(self.registry)
        if method == "GET" and path == "/v1/models":
            for idx in self._ranked():
                try:
                    return self._replicas[idx].endpoint.request(
                        "GET", path, None, timeout_s=self.request_timeout_s)
                except ReplicaTransportError as exc:
                    self._mark_down(idx, str(exc))
            return 503, {"error": "no routable replica"}
        if path.startswith("/v1/models/") and ":" in path and method == "POST":
            rest = path[len("/v1/models/"):]
            name, _, verb = rest.rpartition(":")
            if name and verb == "predict":
                return self._forward_predict(name, body)
            if name and verb in ("publish", "rollback"):
                return self._broadcast(method, path, body, name, verb)
        return 404, {"error": f"no route for {method} {path}"}
