"""FleetRouter: one front door for N replica workers.

The router is the fleet's only stateful coordination point, and it holds
no model state at all — replicas own the models (each a full ServingApp
warmed from the shared AOT bundle), the router owns *placement*:

- **routing**: each predict goes to the routable replica with the lowest
  cost — queued+in-flight rows as of its last health poll, scaled by a
  continuous latency weight from a per-replica windowed latency digest
  (+ the replica's reported queue wait), so a slow-but-alive replica is
  organically drained long before any binary verdict, and re-admitted
  when its (time-windowed) evidence goes stale; ties break round-robin;
- **deadlines**: a predict may carry ``deadline_ms``; the router refuses
  expired requests with 504 before forwarding, derives each hop's HTTP
  read timeout from the remaining budget, and forwards the *remaining*
  budget so the replica's admission check can refuse work it cannot
  finish (see serving/batcher.py);
- **hedging**: when a forwarded predict outlives the target replica's
  own latency quantile (``hedge_quantile`` over its digest), the router
  duplicates it to the next-best replica and takes the first answer —
  bounded by a hedge budget (≤``hedge_budget_pct`` of request volume)
  so hedging can never become the overload;
- **rerouting under a retry budget**: a forwarding failure (connection
  refused/reset — the killed-replica case) marks the replica down
  IMMEDIATELY and retries on the next-best peer; a replica's own
  429/504/5xx reroutes the same way.  Every retry and hedge spends from
  one volume-coupled token bucket (``retry_budget_pct`` of request
  volume), so a fleet-wide brownout degrades to honest 503s instead of
  a retry storm;
- **circuit breakers**: per-replica data-path outcomes feed a
  closed→open→half-open breaker (fleet/breaker.py) — a replica that
  keeps timing out is cut off entirely, probed after a cooldown, and
  re-admitted only when the probes succeed;
- **shedding**: when no replica is routable (all breached/down/broken)
  the router answers 503 at the front door;
- **broadcast**: publish/rollback fan out to EVERY reachable replica so
  a hot-swap lands fleet-wide in one call; publishes ride an idempotent
  ``publish_token`` (minted here when the caller didn't) so stale-conn
  retries, UNKNOWN-outcome re-sends, and rejoin replays can never
  double-apply.

``FleetRouter.handle(method, path, body)`` keeps the same transport-free
contract as ``ServingApp.handle`` — ``serving.server.make_server`` wraps
either, tests drive the router without sockets by injecting fake replica
endpoints, and the router's own gauges (per-replica state/load, forwards,
reroutes, sheds, hedges, router-side latency) live in a telemetry
``MetricsRegistry`` rendered at ``GET /v1/metrics/prometheus``.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from concurrent.futures import FIRST_COMPLETED
from concurrent.futures import TimeoutError as FutureTimeout
from concurrent.futures import wait as futures_wait
from typing import Dict, List, Optional, Tuple

from ..log import LightGBMError, log_info, log_warning
from ..serving.metrics import LatencyWindow
from ..telemetry import trace as _trace
from ..telemetry.registry import MetricsRegistry
from .breaker import CircuitBreaker, LatencyDigest, RetryBudget
from .slo import ReplicaSLO, SLOPolicy, full_forest_affordable

__all__ = ["FleetRouter", "HttpReplica", "ReplicaTransportError"]

# statuses the router treats as "load to place elsewhere", never as the
# request's final answer while peers remain: 429 (queue overflow), 504
# (deadline refused at THAT replica's admission — an idler peer may still
# make it), 5xx (draining / transient)
_RETRYABLE = frozenset({429, 504})


def _retryable(status: int) -> bool:
    return status in _RETRYABLE or status >= 500


class ReplicaTransportError(LightGBMError):
    """The replica could not be reached at all (vs. an HTTP error it
    returned): the router may safely retry elsewhere."""


class HttpReplica:
    """Minimal stdlib HTTP client for one replica endpoint.

    Connections are pooled per (thread, replica) — keep-alive matters at
    soak rates, where a fresh TCP connect per forwarded predict is real
    overhead.  Any socket-level failure drops the pooled connection and
    surfaces as ``ReplicaTransportError`` so the router can distinguish
    "replica gone" (retry elsewhere) from "replica answered an error"
    (forward it); a restarted replica just gets a fresh connection on the
    next call."""

    def __init__(self, url: str, timeout_s: float = 30.0):
        # accept "host:port" or "http://host:port"
        url = url.strip()
        if url.startswith("http://"):
            url = url[len("http://"):]
        url = url.rstrip("/")
        if ":" not in url:
            raise LightGBMError(f"replica url needs host:port, got {url!r}")
        host, port = url.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.name = f"{self.host}:{self.port}"
        self.timeout_s = float(timeout_s)
        self._local = threading.local()
        # bumped via invalidate_pool() when the router learns the replica
        # died or restarted: pooled keep-alive sockets from before then
        # are stale, and a non-retried POST (publish/rollback — retrying
        # could double-apply) written to one fails with a broken pipe
        # even though the replica is back and healthy
        self._gen = 0

    def invalidate_pool(self) -> None:
        """Presume every pooled connection stale; reconnect on next use."""
        self._gen += 1

    def _conn(self, timeout_s: float):
        import http.client
        import socket
        conn = getattr(self._local, "conn", None)
        if conn is not None and getattr(self._local, "gen", -1) != self._gen:
            self._drop_conn()
            conn = None
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=timeout_s)
            conn.connect()
            # TCP_NODELAY: a forwarded predict is one small write per
            # direction — Nagle + delayed ACK otherwise turns each hop
            # into tens of ms of idle waiting
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.conn = conn
            self._local.gen = self._gen
        else:
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:
                pass

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                timeout_s: Optional[float] = None) -> Tuple[int, dict]:
        import http.client
        payload = None if body is None else json.dumps(body).encode()
        headers = ({"Content-Type": "application/json"}
                   if payload is not None else {})
        # one retry on a fresh connection: a pooled keep-alive socket the
        # server closed between calls fails with a reset/EOF that says
        # nothing about the replica's health; a FRESH connect failing is
        # the replica genuinely unreachable — no retry.  Only requests
        # that are safe to EXECUTE TWICE auto-retry: a bare publish/
        # rollback the replica may have already processed before the
        # socket died would double-apply (two version bumps — a later
        # rollback then lands on the duplicate); predicts are pure
        # per-row functions, and a publish carrying a ``publish_token``
        # is idempotent by contract (the registry replays the same
        # version for a token it already applied), so it retries too.
        retry_safe = (method == "GET" or path.endswith(":predict")
                      or path.endswith(":explain")
                      or path.endswith(":rank")
                      or (isinstance(body, dict)
                          and bool(body.get("publish_token"))))
        for attempt in (0, 1):
            reused = getattr(self._local, "conn", None) is not None
            try:
                conn = self._conn(timeout_s or self.timeout_s)
                conn.request(method, path, payload, headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.will_close:
                    self._drop_conn()
                try:
                    return resp.status, json.loads(data)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    # e.g. the Prometheus text route
                    return resp.status, {"text": data.decode(errors="replace")}
            except (OSError, http.client.HTTPException) as exc:
                self._drop_conn()
                # a READ TIMEOUT is not stale-connection evidence: the
                # request reached a live (if slow) replica and re-sending
                # it with a fresh full timeout would both duplicate load
                # outside the router's retry/hedge budgets and double the
                # caller's wait past its deadline — surface it and let
                # the budgeted layers decide (socket.timeout is a
                # TimeoutError subclass since py3.10)
                if (not reused or attempt == 1 or not retry_safe
                        or isinstance(exc, TimeoutError)):
                    raise ReplicaTransportError(
                        f"replica {self.name}: {type(exc).__name__}: "
                        f"{exc}") from exc

    def health(self, timeout_s: float = 2.0) -> Optional[Dict]:
        """The replica's SLO gauges, or None when unreachable/unhealthy."""
        try:
            status, body = self.request("GET", "/v1/fleet/health",
                                        timeout_s=timeout_s)
        except ReplicaTransportError:
            return None
        if status != 200:
            return None
        return body.get("gauges", {})


class _ModelStats:
    """Router-side per-MODEL observables: the fleet counters labeled
    ``model=<name>`` (the unlabeled totals stay for compat) plus the
    windows the derived per-model SLO gauges (p99, deadline-miss ratio,
    goodput) are computed from — the data feed the ROADMAP's
    router-driven placement item needs."""

    __slots__ = ("requests", "reroutes", "shed", "errors", "missed",
                 "outcomes", "latency_hist", "window", "rows", "p99_g",
                 "miss_g", "goodput_g")

    def __init__(self, reg: MetricsRegistry, name: str,
                 verb: str = "predict"):
        lab = {"model": name}
        if verb == "explain":
            # the explain lane is its OWN SLO class: a burst of expensive
            # pred_contrib traffic must show up (and alert) on its own
            # metric family, not dilute the predict lane's p99/miss feed
            # the placement controller reads
            self.requests = reg.counter(
                "lgbm_fleet_explain_requests_total",
                "explain (pred_contrib) requests at the router", **lab)
            self.reroutes = reg.counter(
                "lgbm_fleet_explain_reroutes_total",
                "explain forwards retried on another replica after a "
                "failure", **lab)
            self.shed = reg.counter(
                "lgbm_fleet_explain_shed_total",
                "explain requests shed because no replica was within SLO",
                **lab)
            self.errors = reg.counter(
                "lgbm_fleet_explain_errors_total",
                "explain requests that failed on every routable replica",
                **lab)
            self.missed = reg.counter(
                "lgbm_fleet_explain_deadline_missed_total",
                "explain requests that ended 504 (deadline verdict "
                "anywhere along the chain)", **lab)
            self.latency_hist = reg.histogram(
                "lgbm_fleet_explain_request_latency_seconds",
                "router-side end-to-end explain latency", **lab)
        elif verb == "rank":
            # the rank lane is likewise its own SLO class: a :rank
            # request is a whole query group, so its latency/goodput
            # economics (rows follow query length) must not dilute the
            # predict feed the placement controller reads
            self.requests = reg.counter(
                "lgbm_fleet_rank_requests_total",
                "rank (query scoring) requests at the router", **lab)
            self.reroutes = reg.counter(
                "lgbm_fleet_rank_reroutes_total",
                "rank forwards retried on another replica after a "
                "failure", **lab)
            self.shed = reg.counter(
                "lgbm_fleet_rank_shed_total",
                "rank requests shed because no replica was within SLO",
                **lab)
            self.errors = reg.counter(
                "lgbm_fleet_rank_errors_total",
                "rank requests that failed on every routable replica",
                **lab)
            self.missed = reg.counter(
                "lgbm_fleet_rank_deadline_missed_total",
                "rank requests that ended 504 (deadline verdict "
                "anywhere along the chain)", **lab)
            self.latency_hist = reg.histogram(
                "lgbm_fleet_rank_request_latency_seconds",
                "router-side end-to-end rank latency", **lab)
        else:
            self.requests = reg.counter(
                "lgbm_fleet_requests_total",
                "predict requests at the router", **lab)
            self.reroutes = reg.counter(
                "lgbm_fleet_reroutes_total",
                "forwards retried on another replica after a failure",
                **lab)
            self.shed = reg.counter(
                "lgbm_fleet_shed_total",
                "requests shed because no replica was within SLO", **lab)
            self.errors = reg.counter(
                "lgbm_fleet_errors_total",
                "requests that failed on every routable replica", **lab)
            self.missed = reg.counter(
                "lgbm_fleet_model_deadline_missed_total",
                "requests for this model that ended 504 (deadline verdict "
                "anywhere along the chain)", **lab)
            self.latency_hist = reg.histogram(
                "lgbm_fleet_request_latency_seconds",
                "router-side end-to-end predict latency", **lab)
        # recent-evidence windows behind the derived gauges: time-bounded
        # so an idle model's gauges decay instead of freezing on history
        # (an all-time miss ratio would pin one early 504 burst on the
        # placement feed for the process's whole lifetime).  The miss
        # ratio reads ONE outcome ring (1.0 = 504, 0.0 = anything else):
        # numerator and denominator come from the same samples, so ring
        # saturation cannot skew the ratio — it just shortens the
        # effective window above ~cap/window_s requests per second
        self.window = LatencyWindow(2048, window_s=60.0)
        self.rows = LatencyWindow(8192, window_s=30.0)
        self.outcomes = LatencyWindow(8192, window_s=60.0)
        if verb == "explain":
            self.p99_g = reg.gauge(
                "lgbm_fleet_explain_p99_ms",
                "per-model explain SLO gauge: p99 of recent router-side "
                "explain latencies (ms), failures included", **lab)
            self.miss_g = reg.gauge(
                "lgbm_fleet_explain_deadline_miss_ratio",
                "per-model explain SLO gauge: fraction of recent-window "
                "explain requests that ended 504", **lab)
            self.goodput_g = reg.gauge(
                "lgbm_fleet_explain_goodput_rows_per_s",
                "per-model explain SLO gauge: contribution rows answered "
                "200 per second over the recent window", **lab)
        elif verb == "rank":
            self.p99_g = reg.gauge(
                "lgbm_fleet_rank_p99_ms",
                "per-model rank SLO gauge: p99 of recent router-side "
                "rank latencies (ms), failures included", **lab)
            self.miss_g = reg.gauge(
                "lgbm_fleet_rank_deadline_miss_ratio",
                "per-model rank SLO gauge: fraction of recent-window "
                "rank requests that ended 504", **lab)
            self.goodput_g = reg.gauge(
                "lgbm_fleet_rank_goodput_rows_per_s",
                "per-model rank SLO gauge: query-group rows answered "
                "200 per second over the recent window", **lab)
        else:
            self.p99_g = reg.gauge(
                "lgbm_fleet_model_p99_ms",
                "per-model SLO gauge: p99 of recent router-side latencies "
                "(ms), failures included", **lab)
            self.miss_g = reg.gauge(
                "lgbm_fleet_model_deadline_miss_ratio",
                "per-model SLO gauge: fraction of recent-window requests "
                "that ended 504", **lab)
            self.goodput_g = reg.gauge(
                "lgbm_fleet_model_goodput_rows_per_s",
                "per-model SLO gauge: rows answered 200 per second over "
                "the recent window", **lab)

    def refresh(self) -> None:
        self.p99_g.set(self.window.percentiles()["p99_ms"])
        n = self.outcomes.window_count()
        self.miss_g.set(self.outcomes.window_sum() / n if n else 0.0)
        self.goodput_g.set(self.rows.window_sum()
                           / (self.rows.window_s or 1.0))


class _Replica:
    """Router-side record: endpoint + SLO state + last-known load."""

    def __init__(self, endpoint, slo: ReplicaSLO, breaker: CircuitBreaker,
                 digest: LatencyDigest):
        self.endpoint = endpoint
        self.slo = slo
        self.breaker = breaker          # data-path closed/open/half-open
        self.digest = digest            # windowed data-path latencies
        self.queue_wait_ms = 0.0        # replica-reported, at last poll
        self.load_rows = 0        # queued + in-flight rows at last poll
        # rows forwarded by THIS router and not yet answered: the live
        # complement to load_rows, which refreshes only at poll time —
        # without it every request between two polls ranks the same
        # replica first and herds onto it for a full poll interval
        self.router_inflight_rows = 0
        self.last_poll_s = 0.0
        # restart evidence gating publish replay, so a transient
        # health-poll blip doesn't trigger a redundant publish that
        # desynchronizes version counters fleet-wide.  Primary signal:
        # the replica's boot_s gauge (a restarted replica is a fresh
        # process with a new boot time — works even before it serves
        # its first request).  Fallback for gauge sources without
        # boot_s: a rejoining replica reporting FEWER cumulative
        # requests than this high-water mark was genuinely restarted.
        self.boot_s: Optional[float] = None
        self.requests_high = 0
        # set by retire_replica (autoscale scale-down): permanently out
        # of rotation — never ranked, never polled, never broadcast to.
        # The slot stays in the list so every index-parallel structure
        # (metric lists, placement sets, supervisor slots) stays valid
        self.retired = False


class FleetRouter:
    def __init__(self, replicas: List, policy: Optional[SLOPolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 poll_interval_ms: float = 100.0,
                 request_timeout_s: float = 30.0,
                 health_timeout_s: float = 2.0,
                 autostart: bool = True,
                 hedge_quantile: float = 0.95,
                 hedge_min_ms: float = 20.0,
                 hedge_budget_pct: float = 5.0,
                 retry_budget_pct: float = 10.0,
                 breaker_failures: int = 5,
                 breaker_cooldown_s: float = 2.0,
                 breaker_probes: int = 2,
                 latency_routing: bool = True,
                 default_deadline_ms: float = 0.0,
                 supervisor=None,
                 tracer=None,
                 cascade_mode: str = "off"):
        if not replicas:
            raise LightGBMError("FleetRouter needs at least one replica")
        policy = policy or SLOPolicy()
        # kept for add_replica: a scaled-up replica gets the same breaker
        # tuning as the launch-time set
        self._breaker_args = dict(failures=breaker_failures,
                                  cooldown_s=breaker_cooldown_s,
                                  probes=breaker_probes)
        self._replicas = [
            _Replica(ep, ReplicaSLO(policy),
                     CircuitBreaker(**self._breaker_args), LatencyDigest())
            for ep in replicas]
        self.policy = policy
        self.registry = registry or MetricsRegistry()
        self.poll_interval_s = float(poll_interval_ms) / 1e3
        self.request_timeout_s = float(request_timeout_s)
        self.health_timeout_s = float(health_timeout_s)
        # gray-failure knobs (fleet/breaker.py has the semantics):
        # hedge_quantile=0 disables hedging, retry_budget_pct=0 restores
        # unbounded reroutes, breaker_failures=0 disables the breakers,
        # latency_routing=False restores pure least-loaded ranking —
        # together these knobs are the bench's "un-hardened" contrast
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_ms = float(hedge_min_ms)
        self.latency_routing = bool(latency_routing)
        self.default_deadline_ms = float(default_deadline_ms)
        # early-exit cascade: in "deadline" mode a request whose budget
        # cannot afford the full forest (per-model p99 evidence) is
        # forwarded with degrade=true and served the calibrated prefix
        # answer instead of a 504 (serving/cascade.py has the band math)
        self.cascade_mode = str(cascade_mode or "off")
        self.retry_budget = RetryBudget(ratio=retry_budget_pct / 100.0)
        self.hedge_budget = RetryBudget(ratio=hedge_budget_pct / 100.0,
                                        cap=50.0, initial=5.0)
        self.supervisor = supervisor   # abandoned-slot visibility only
        # distributed tracing: the router MINTS each predict's trace and
        # stamps every routing decision on it (telemetry/trace.py);
        # replicas adopt the context forwarded in the request body
        self.tracer = tracer if tracer is not None else _trace.TRACER
        self._per_model: Dict[str, _ModelStats] = {}
        self._lock = threading.Lock()
        self._rr = 0                      # round-robin tie-breaker
        self._next_demand_poll_s = 0.0    # rate limit for pollless mode
        self._started = False
        self._closed = False
        # last successful publish body per model name: replayed to a
        # replica that comes back from DOWN, because a supervised restart
        # respawns it from its ORIGINAL argv — without the replay it
        # would rejoin serving the pre-hot-swap model indefinitely
        self._published: Dict[str, dict] = {}
        # placement table (the multi-tenant control plane's output):
        # model name -> frozenset of replica indices that host it.  A
        # model with NO entry is "everywhere" — the broadcast-publish
        # default — so the table only constrains models the placement
        # controller has narrowed.  Flipped atomically per move (one
        # dict store under the lock); _ranked consults it per request
        self._placement: Dict[str, frozenset] = {}
        # last fleet-confirmed version per model (broadcast publishes
        # and controller moves both maintain it) — the version column
        # of GET /v1/fleet/models
        self._model_versions: Dict[str, int] = {}
        from concurrent.futures import ThreadPoolExecutor
        # SEPARATE pools for health sweeps and publish broadcasts: a
        # publish occupies a worker for up to request_timeout_s per
        # replica (model load + warmup), and health probes queued behind
        # broadcasts would time out and flap perfectly healthy replicas
        # down fleet-wide — no shared sizing is safe against two
        # overlapping broadcasts, so the sweep gets its own workers
        self._health_pool = ThreadPoolExecutor(
            max_workers=max(len(replicas), 2),
            thread_name_prefix="lgbm-tpu-fleet-health")
        self._bcast_pool = ThreadPoolExecutor(
            max_workers=max(len(replicas), 2),
            thread_name_prefix="lgbm-tpu-fleet-bcast")
        # hedged forwards need the primary on a worker thread (the caller
        # waits out the hedge delay, then maybe races a duplicate); only
        # the hedgeable path pays it — un-hedgeable forwards stay
        # inline, and a SATURATED pool also falls back to inline (see
        # _attempt_maybe_hedged) so the pool size caps hedging, never
        # the router's total concurrency
        self._hedge_capacity = max(8 * len(replicas), 32)
        self._hedge_inflight = 0          # guarded by self._lock
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=self._hedge_capacity,
            thread_name_prefix="lgbm-tpu-fleet-hedge")
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()
        self.latency = LatencyWindow()
        # router-side observables, labeled per replica where meaningful
        reg = self.registry
        self._m_requests = reg.counter(
            "lgbm_fleet_requests_total", "predict requests at the router")
        self._m_shed = reg.counter(
            "lgbm_fleet_shed_total",
            "requests shed because no replica was within SLO")
        self._m_reroutes = reg.counter(
            "lgbm_fleet_reroutes_total",
            "forwards retried on another replica after a failure")
        self._m_errors = reg.counter(
            "lgbm_fleet_errors_total",
            "requests that failed on every routable replica")
        self._m_publish_partial = reg.counter(
            "lgbm_fleet_publish_partial_total",
            "publish broadcasts that landed on only a subset of replicas "
            "and were rolled back to keep the fleet single-version")
        self._m_latency = reg.histogram(
            "lgbm_fleet_request_latency_seconds",
            "router-side end-to-end predict latency")
        self._m_hedges = reg.counter(
            "lgbm_fleet_hedges_total",
            "predicts duplicated to a second replica after the primary "
            "outlived its latency-quantile hedge delay")
        self._m_hedge_wins = reg.counter(
            "lgbm_fleet_hedge_wins_total",
            "hedged predicts where the duplicate answered first")
        self._m_hedge_denied = reg.counter(
            "lgbm_fleet_hedge_denied_total",
            "hedges skipped because the hedge/retry budget was spent")
        self._m_retry_denied = reg.counter(
            "lgbm_fleet_retry_budget_exhausted_total",
            "requests answered 503 because the shared retry budget had "
            "no token for another attempt (brownout backpressure)")
        self._m_deadline = reg.counter(
            "lgbm_fleet_deadline_refused_total",
            "predicts refused 504 at the router because their deadline "
            "budget was already spent")
        self._m_degraded = reg.counter(
            "lgbm_fleet_degraded_total",
            "predicts forwarded degrade=true because their remaining "
            "budget could not afford the full forest (served the "
            "calibrated prefix answer instead of a 504)")
        self._m_forwarded = [reg.counter(
            "lgbm_fleet_forwarded_total", "predicts forwarded",
            replica=r.endpoint.name) for r in self._replicas]
        self._m_up = [reg.gauge(
            "lgbm_fleet_replica_up",
            "1 routable / 0 shed or down", replica=r.endpoint.name)
            for r in self._replicas]
        self._m_load = [reg.gauge(
            "lgbm_fleet_replica_load_rows",
            "queued+in-flight rows at last poll",
            replica=r.endpoint.name) for r in self._replicas]
        self._m_p99 = [reg.gauge(
            "lgbm_fleet_replica_p99_ms", "replica p99 at last poll",
            replica=r.endpoint.name) for r in self._replicas]
        self._m_fill = [reg.gauge(
            "lgbm_fleet_replica_batch_fill",
            "replica in-flight batch fill at last poll",
            replica=r.endpoint.name) for r in self._replicas]
        self._m_breaker = [reg.gauge(
            "lgbm_fleet_replica_breaker_state",
            "data-path circuit breaker: 0 closed / 1 half-open / 2 open",
            replica=r.endpoint.name) for r in self._replicas]
        for g in self._m_up:
            g.set(1)                       # optimistic, like ReplicaSLO
        if autostart:
            self.start()

    # ------------------------------------------------------------------
    def start(self) -> "FleetRouter":
        self._started = True
        if self._poll_thread is None and self.poll_interval_s > 0:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="lgbm-tpu-fleet-poll",
                daemon=True)
            self._poll_thread.start()
        return self

    def close(self) -> None:
        self._closed = True
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10.0)
            self._poll_thread = None
        self._health_pool.shutdown(wait=False)
        self._bcast_pool.shutdown(wait=False)
        self._hedge_pool.shutdown(wait=False)

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    def poll_once(self) -> None:
        """One health sweep: refresh every replica's SLO state + gauges.
        Public so tests (and a pollless router) can drive it directly.

        Health requests go out IN PARALLEL (persistent worker pool, so
        the per-thread connection pooling still applies): each can block
        up to health_timeout_s, and one hung replica must not stretch
        every other replica's detection/recovery hysteresis by its
        timeout."""
        reps = list(self._replicas)    # autoscale may append concurrently
        futures = [None if rep.retired
                   else self._health_pool.submit(rep.endpoint.health,
                                                 self.health_timeout_s)
                   for rep in reps]
        for i, rep in enumerate(reps):
            if futures[i] is None:
                continue
            try:
                gauges = futures[i].result(self.health_timeout_s + 5.0)
            except Exception:
                gauges = None
            with self._lock:
                before = rep.slo.state
                state = rep.slo.observe(gauges)
                rep.last_poll_s = time.time()
                requests = (int(gauges.get("requests", 0))
                            if gauges is not None else 0)
                # replay only on evidence of a real restart (a transient
                # poll blip must not trigger a redundant publish):
                # boot_s changed when available — never-seen counts as
                # changed, a down replica we know nothing about may have
                # missed a publish — else the requests-drop heuristic
                if gauges is not None and "boot_s" in gauges:
                    restarted = gauges["boot_s"] != rep.boot_s
                else:
                    restarted = requests < rep.requests_high
                replay = (before == "down" and gauges is not None
                          and bool(self._published) and restarted)
                # placement-filtered: a rejoining replica only gets the
                # models PLACED on it (or unplaced ones — broadcast
                # default); replaying a model placed elsewhere would
                # undo the controller's unpublish on this replica
                published = ({n: dict(b)
                              for n, b in self._published.items()
                              if self._placement.get(n) is None
                              or i in self._placement[n]}
                             if replay else None)
                if gauges is None or restarted:
                    # every pooled keep-alive socket predating a death /
                    # restart is stale; reconnect lazily (publishes are
                    # not retried on stale sockets — see HttpReplica)
                    invalidate = getattr(rep.endpoint, "invalidate_pool",
                                         None)
                    if invalidate is not None:
                        invalidate()
                if gauges is not None:
                    rep.boot_s = gauges.get("boot_s", rep.boot_s)
                    if replay:
                        rep.requests_high = requests
                    else:
                        rep.requests_high = max(rep.requests_high,
                                                requests)
                    rep.load_rows = (int(gauges.get("queue_rows", 0))
                                     + int(gauges.get("inflight_rows", 0)))
                    rep.queue_wait_ms = float(
                        gauges.get("queue_wait_ms", 0.0))
                    self._m_load[i].set(rep.load_rows)
                    self._m_p99[i].set(float(gauges.get("p99_ms", 0.0)))
                    self._m_fill[i].set(float(gauges.get("batch_fill", 0.0)))
                self._m_up[i].set(1 if rep.slo.routable else 0)
                self._m_breaker[i].set(
                    {"closed": 0, "half_open": 1, "open": 2}.get(
                        rep.breaker.state, 0))
            if replay:
                # back from the dead: a supervised restart reloaded the
                # replica's ORIGINAL models, so hot-swaps it missed must
                # be replayed before it takes real traffic (it is still
                # in shed for recover_polls polls — the replay usually
                # wins that race, and a lost race only serves the old
                # version briefly, same as before the swap landed)
                threading.Thread(target=self._replay_publishes,
                                 args=(rep, published), daemon=True,
                                 name="lgbm-tpu-fleet-replay").start()
            if state != before:
                (log_warning if state != "healthy" else log_info)(
                    f"fleet: replica {rep.endpoint.name} {before} -> "
                    f"{state} ({'; '.join(rep.slo.last_reasons) or 'ok'})")

    def _replay_publishes(self, rep, published: Dict[str, dict]) -> None:
        for name in published:
            # re-read the cache at send time, and re-send if a concurrent
            # fleet-wide publish moved it while our replay was in flight —
            # otherwise the replay could land AFTER a newer broadcast and
            # pin this one replica on the older version until its next
            # restart.  Bounded: a live system converges in one pass.
            for _ in range(3):
                with self._lock:
                    body = self._published.get(name)
                if body is None:          # rolled back meanwhile
                    break
                try:
                    status, _ = rep.endpoint.request(
                        "POST", f"/v1/models/{name}:publish", body,
                        timeout_s=self.request_timeout_s)
                    (log_info if status == 200 else log_warning)(
                        f"fleet: replayed publish of {name!r} to rejoined "
                        f"replica {rep.endpoint.name} (status {status})")
                except ReplicaTransportError as exc:
                    log_warning(f"fleet: publish replay of {name!r} to "
                                f"{rep.endpoint.name} failed: {exc}")
                    break
                with self._lock:
                    if self._published.get(name) == body:
                        break             # cache unchanged: we sent latest

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as exc:     # a poll bug must not kill routing
                log_warning(f"fleet: health poll failed: {exc!r}")

    # ------------------------------------------------------------------
    _DEMAND_POLL_MIN_INTERVAL_S = 1.0

    def _maybe_poll_inline(self) -> None:
        """fleet_poll_ms=0 runs no poll thread, so health state refreshes
        ON DEMAND here instead: recovery (down -> shed -> healthy) only
        happens inside ReplicaSLO.observe, which only poll_once calls —
        without this, one transport blip would shed a replica forever.
        Rate-limited so a down replica costs at most one health sweep per
        interval, not one per request.  Only active on a STARTED router:
        an unstarted one (autostart=False, tests) is under manual
        poll_once control."""
        if (not self._started or self._poll_thread is not None
                or self._closed):
            return
        now = time.time()
        with self._lock:
            need = any(not rep.slo.routable or rep.last_poll_s == 0.0
                       for rep in self._replicas)
            if not need or now < self._next_demand_poll_s:
                return
            self._next_demand_poll_s = now + self._DEMAND_POLL_MIN_INTERVAL_S
        self.poll_once()

    # a gray replica's latency weight is capped: 100x the fleet-best is
    # already "drained"; unbounded weights would just overflow the sort
    _LATENCY_WEIGHT_CAP = 100.0
    # a timeout only counts as breaker evidence when the replica had at
    # least this much allowance (see _attempt)
    _BREAKER_TIMEOUT_FLOOR_S = 1.0
    # a timed-out attempt's latency sample is censored ("at least this
    # slow"); it enters the digest scaled by this factor (see _attempt)
    _TIMEOUT_LATENCY_PENALTY = 4.0
    # cost floor in row units — roughly one batch's worth of work.  The
    # latency weight multiplies (load + floor), so a 20x-slower replica
    # is NOT re-picked just because the fast replica has a normal
    # batch's worth of rows queued (load and weight live in different
    # units; without the floor ~20 queued rows outvoted a 20x latency
    # ratio).  Among equal-latency replicas the floor shifts every cost
    # equally, so least-loaded ordering is unchanged
    _LOAD_FLOOR_ROWS = 64.0

    def _latency_weights(self, indices: List[int]) -> Dict[int, float]:
        """Continuous routing weight per replica: observed data-path p50
        (windowed digest) plus the replica's own reported queue wait,
        relative to the fleet's best.  A replica with no RECENT evidence
        (drained, or never probed) weighs 1.0 — neutral, so it gets
        probed again instead of being exiled on stale history."""
        if not self.latency_routing:
            return {i: 1.0 for i in indices}
        cost: Dict[int, Optional[float]] = {}
        for i in indices:
            rep = self._replicas[i]
            p50 = rep.digest.quantile(0.5)
            # max, not sum: the router-observed p50 is a full round trip
            # and already CONTAINS the replica's queue wait — summing
            # would double-count congestion (and the load term counts it
            # a third time).  The replica-reported figure still matters
            # as the fresher signal when the router's own observations
            # lag the replica's true state
            cost[i] = (None if p50 is None
                       else max(p50 * 1e3, rep.queue_wait_ms))
        known = [c for c in cost.values() if c is not None and c > 0]
        if not known:
            return {i: 1.0 for i in indices}
        best = min(known)
        return {i: (1.0 if c is None
                    else min(max(c / best, 1.0), self._LATENCY_WEIGHT_CAP))
                for i, c in cost.items()}

    def _ranked(self, model: Optional[str] = None) -> List[int]:
        """Routable replica indices, cheapest first (round-robin among
        equals so idle replicas share traffic).  Cost is the replica's
        last-polled queue+in-flight rows PLUS rows this router has
        forwarded since and not yet heard back about — the live term is
        what spreads concurrent requests between polls — scaled by the
        continuous latency weight, so a slow-but-alive replica needs to
        be proportionally idler before it wins a request.  Replicas whose
        circuit breaker is open (and not yet due a half-open probe) are
        excluded outright, as are retired (scaled-down) slots.

        With ``model``, candidates are further gated by the placement
        table: a placed model routes ONLY to its assigned replicas (the
        others unpublished it — forwarding there would 404, a verdict
        the retry loop treats as final).  A model without a placement
        entry routes fleet-wide, the broadcast-publish default."""
        self._maybe_poll_inline()
        with self._lock:
            self._rr += 1
            placed = (self._placement.get(model)
                      if model is not None else None)
            candidates = [(i, rep.load_rows + rep.router_inflight_rows,
                           rep.breaker.wants_probe())
                          for i, rep in enumerate(self._replicas)
                          if not rep.retired and rep.slo.routable
                          and rep.breaker.admits()
                          and (placed is None or i in placed)]
        weights = self._latency_weights([i for i, _, _ in candidates])
        # probe priority: a half-open replica with free probe slots must
        # actually RECEIVE a request to prove itself, and a slow/drained
        # replica never wins the cost comparison on its own — rank it
        # first (bounded: try_acquire grants at most `probes` concurrent
        # trials, everything else reroutes normally)
        order = [(-1.0 if probe
                  else (load + self._LOAD_FLOOR_ROWS) * weights[i],
                  (i + self._rr) % len(self._replicas), i)
                 for i, load, probe in candidates]
        return [i for _, _, i in sorted(order)]

    # label-cardinality bound: the router counts BEFORE any replica can
    # 404 an unknown name, so sustained typo'd traffic must not mint an
    # unbounded registry family per distinct name — past the cap, new
    # names share one "_other" row
    _MAX_MODEL_LABELS = 256

    def _model_stats(self, name: str,
                     verb: str = "predict") -> _ModelStats:
        """Per-model fleet metrics, created on first touch.  Lock-free
        read on the hot path (CPython dict get); creation double-checks
        under the router lock.  The explain lane keeps its own row per
        model (key ``name:explain``) so its SLO windows and counters
        never mix with the predict lane's — route parsing rejects names
        containing ``:``, so the suffix cannot collide with a real
        model."""
        key = name if verb == "predict" else f"{name}:{verb}"
        m = self._per_model.get(key)
        if m is not None:
            return m
        with self._lock:
            m = self._per_model.get(key)
            if m is None:
                if len(self._per_model) >= self._MAX_MODEL_LABELS:
                    name = "_other"
                    key = name if verb == "predict" else f"{name}:{verb}"
                    m = self._per_model.get(key)
                if m is None:
                    m = self._per_model[key] = _ModelStats(
                        self.registry, name, verb)
            return m

    def refresh_model_gauges(self) -> None:
        """Recompute the derived per-model SLO gauges from the live
        windows — called at metrics render, not per request."""
        for m in list(self._per_model.values()):
            m.refresh()

    def _mark_down(self, idx: int, reason: str) -> None:
        rep = self._replicas[idx]
        with self._lock:
            rep.slo.mark_down(reason)
            self._m_up[idx].set(0)
        invalidate = getattr(rep.endpoint, "invalidate_pool", None)
        if invalidate is not None:
            invalidate()
        log_warning(f"fleet: replica {rep.endpoint.name} marked down "
                    f"({reason})")

    def _attempt(self, idx: int, name: str, body: dict, nrows: int,
                 timeout_s: float,
                 started: Optional[threading.Event] = None,
                 tspan=None,
                 verb: str = "predict") -> Tuple[Optional[int], dict]:
        """One forward to one replica with full gray-failure accounting:
        breaker admission, live in-flight rows, latency digest feed, and
        the transport-error split — a TIMEOUT feeds the breaker/digest
        but does NOT mark the replica down (it is alive; its health polls
        keep passing — that is the gray failure), while a refused/reset
        connection is the killed-replica case and demotes immediately.
        Returns (status, payload); status None = transport failure.

        With a trace span (``tspan``, the request's root), the attempt
        gets its own child span and the forwarded body carries its wire
        context, so the replica's spans nest under THIS attempt — a
        hedged request's two attempts stay distinguishable."""
        if started is not None:
            started.set()   # hedge-delay clock starts at real execution
        rep = self._replicas[idx]
        grant = rep.breaker.try_acquire()
        probe = grant == CircuitBreaker.GRANT_PROBE
        if not grant:
            # lost a race for the last half-open probe slot: the request
            # was never sent anywhere — flagged so the forward loop can
            # move on WITHOUT charging the retry budget or counting an
            # attempt (under a brownout that charge would 503 a request
            # no replica ever even received)
            return None, {"error": f"replica {rep.endpoint.name}: "
                                   "circuit breaker open",
                          "breaker_race": True}
        aspan = None
        if tspan is not None:
            aspan = tspan.child("router.attempt",
                                replica=rep.endpoint.name, probe=probe,
                                timeout_ms=round(timeout_s * 1e3, 1))
            body = dict(body)
            body[_trace.BODY_KEY] = aspan.wire()
            if probe or rep.breaker.state != "closed":
                tspan.mark("breaker")
        with self._lock:
            rep.router_inflight_rows += nrows
        t0 = time.perf_counter()
        try:
            status, payload = rep.endpoint.request(
                "POST", f"/v1/models/{name}:{verb}", body,
                timeout_s=timeout_s)
        except ReplicaTransportError as exc:
            if aspan is not None:
                aspan.set(error=str(exc))
                aspan.finish()
            if isinstance(exc.__cause__, TimeoutError):
                # count the wait as a latency sample: "at least this
                # slow" is exactly the evidence that drains a gray
                # replica even when nothing ever hard-fails.  The sample
                # is CENSORED (the truth is >= the timeout, usually much
                # more), so it goes in with a penalty factor — under
                # uniformly tight deadlines the raw squeezed timeout
                # would cap the digest near the healthy replicas' p50
                # and collapse the drain weight exactly when it matters.
                # Breaker evidence only when the replica had a
                # REASONABLE allowance — a timeout under a deadline-
                # squeezed sub-second budget is the deadline's verdict
                # on the request, not the replica's health (an overload
                # storm of impatient clients must not breaker-open the
                # whole fleet into a full outage)
                rep.digest.observe((time.perf_counter() - t0)
                                   * self._TIMEOUT_LATENCY_PENALTY)
                if timeout_s >= self._BREAKER_TIMEOUT_FLOOR_S:
                    rep.breaker.record_failure(probe)
                else:
                    rep.breaker.record_neutral(probe)
            else:
                rep.breaker.record_failure(probe)
                self._mark_down(idx, str(exc))
            if rep.breaker.state == "open":
                # a breaker just opened (or re-opened): failure burst —
                # snapshot the flight recorder while the evidence is
                # still in the ring (rate-limited, needs trace_dir)
                self.tracer.maybe_dump("breaker_open")
            return None, {"error": str(exc)}
        finally:
            with self._lock:
                rep.router_inflight_rows -= nrows
        elapsed = time.perf_counter() - t0
        if status == 200:
            rep.digest.observe(elapsed)
            rep.breaker.record_success(probe)
        elif status >= 500 and status != 504:
            # 5xx = the replica itself is failing.  NOT 504 (that is the
            # DEADLINE's verdict on the request's budget, not the
            # replica's health — under a storm of impatient clients every
            # replica would "fail" and the breakers would turn partial
            # degradation into a full outage) and NOT 429 (queue-full is
            # congestion the SLO shed machine already handles from the
            # polled gauges); both still reroute, they just aren't
            # breaker evidence
            rep.breaker.record_failure(probe)
            if rep.breaker.state == "open":
                self.tracer.maybe_dump("breaker_open")
        else:
            # neutral outcome (429/504/4xx): in half-open this releases
            # the probe slot the attempt consumed
            rep.breaker.record_neutral(probe)
        if aspan is not None:
            aspan.set(status=status)
            aspan.finish()
        return status, payload

    def _hedge_delay_s(self, idx: int) -> Optional[float]:
        """How long to let a forward to `idx` run before duplicating it,
        from the replica's OWN latency quantile — None disables hedging
        for this attempt (knob off, single replica, or a digest without
        enough recent samples to name a quantile: hedging on no evidence
        would duplicate every request)."""
        if self.hedge_quantile <= 0 or len(self._replicas) < 2:
            return None
        q = self._replicas[idx].digest.quantile(self.hedge_quantile)
        if q is None:
            return None
        return max(q, self.hedge_min_ms / 1e3)

    def _hedge_submit(self, *attempt_args):
        """Submit one _attempt to the hedge pool, maintaining the
        router's own in-flight count (the saturation signal for the
        inline fallback)."""
        with self._lock:
            self._hedge_inflight += 1
        try:
            fut = self._hedge_pool.submit(self._attempt, *attempt_args)
        except BaseException:
            with self._lock:
                self._hedge_inflight -= 1
            raise

        def _done(_f):
            with self._lock:
                self._hedge_inflight -= 1

        fut.add_done_callback(_done)
        return fut

    def _attempt_maybe_hedged(self, idx: int, name: str, body: dict,
                              nrows: int, timeout_s: float, tried: set,
                              deadline_t: Optional[float] = None,
                              tspan=None, verb: str = "predict"
                              ) -> List[Tuple[int, Optional[int], dict]]:
        """Forward to `idx`, duplicating to the next-best peer if the
        primary outlives its hedge delay and the hedge + retry budgets
        both grant a token.  Returns the observed outcomes as
        (replica_idx, status, payload) — the FIRST decisive (non-
        retryable) answer short-circuits; a hedged request's loser is
        abandoned to finish on its own (its accounting resolves in
        _attempt).  Adds any hedged replica to `tried`."""
        delay = self._hedge_delay_s(idx)
        saturated = False
        if delay is not None:
            with self._lock:
                saturated = self._hedge_inflight >= self._hedge_capacity
        if delay is None or delay >= timeout_s or saturated:
            # pool saturated = more hedgeable forwards than workers: run
            # inline (forfeit hedging for THIS request) rather than
            # queue — a queued primary stalls behind strangers' HTTP
            # calls with its deadline already stamped, and the pool
            # would otherwise cap the router's total concurrency.
            # Tracked with the router's own in-flight counter, not the
            # executor's private internals
            return [(idx, *self._attempt(idx, name, body, nrows,
                                         timeout_s, None, tspan, verb))]
        started = threading.Event()
        primary = self._hedge_submit(idx, name, body, nrows, timeout_s,
                                     started, tspan, verb)
        # an attempt can legitimately run ~2x its HTTP timeout (the
        # stale-conn retry inside HttpReplica) — the hard waits below
        # must outlast that, and a primary that never answers within
        # them is reported as a stalled-attempt failure, NOT an escaped
        # FutureTimeout turning a retryable situation into a 500
        hard_wait = 2.0 * timeout_s + 5.0
        try:
            st, pl = primary.result(timeout=delay)
            return [(idx, st, pl)]
        except FutureTimeout:
            pass
        def _await_primary():
            """Wait out the primary (bounded by hard_wait); a primary
            that never answers becomes a retryable stalled-attempt
            failure, not an escaped FutureTimeout 500."""
            try:
                st, pl = primary.result(timeout=hard_wait)
            except FutureTimeout:
                return [(idx, None, {"error": "attempt stalled past its "
                                              "transport timeout"})]
            return [(idx, st, pl)]

        alt = None
        if started.is_set():
            # only hedge against a primary that actually STARTED — a
            # saturated hedge pool makes queued primaries "outlive" any
            # delay, and duplicating load precisely when the system is
            # saturated would amplify the overload, not relieve it
            alt = next((i for i in self._ranked(name) if i not in tried),
                       None)
        if alt is not None:
            alt_p50 = self._replicas[alt].digest.quantile(0.5)
            if alt_p50 is not None and alt_p50 > delay:
                # the only peer left is EXPECTED to be slower than the
                # delay we already waited — a duplicate there cannot
                # plausibly win, so spending hedge budget (and loading
                # the slow replica) buys nothing
                alt = None
        granted = alt is not None and self.hedge_budget.try_spend()
        if granted and not self.retry_budget.try_spend():
            self.hedge_budget.refund()
            granted = False
        if not granted:
            if alt is not None:
                self._m_hedge_denied.inc()
                if tspan is not None:
                    tspan.event("router.hedge_denied",
                                replica=self._replicas[alt].endpoint.name)
            return _await_primary()
        hbody, h_timeout = body, timeout_s
        if deadline_t is not None:
            # the budget in `body` was stamped BEFORE the hedge delay
            # elapsed — forwarding it verbatim would overstate what is
            # left and let the alt replica admit (and compute) work
            # whose real deadline has already passed
            rem = deadline_t - time.perf_counter()
            if rem <= 0:
                self.hedge_budget.refund()
                self.retry_budget.refund()
                return _await_primary()
            hbody = dict(body)
            hbody["deadline_ms"] = rem * 1e3
            h_timeout = min(timeout_s, rem)
        tried.add(alt)
        self._m_hedges.inc()
        if tspan is not None:
            # mark BEFORE the duplicate is sent: its wire context then
            # carries the keep hint, so the hedge target persists its
            # half of a trace this router already decided matters
            tspan.mark("hedged")
            tspan.event("router.hedge",
                        replica=self._replicas[alt].endpoint.name,
                        delay_ms=round(delay * 1e3, 2))
        hedge = self._hedge_submit(alt, name, hbody, nrows, h_timeout,
                                   None, tspan, verb)
        futs = {primary: idx, hedge: alt}
        outcomes: List[Tuple[int, Optional[int], dict]] = []
        pending = set(futs)
        deadline = time.perf_counter() + hard_wait
        while pending:
            done, pending = futures_wait(
                pending, timeout=max(deadline - time.perf_counter(), 0.1),
                return_when=FIRST_COMPLETED)
            if not done:
                break   # both wedged past their own HTTP timeouts
            # both may land in one wait round: prefer the PRIMARY so the
            # served answer and the hedge-win credit don't depend on set
            # iteration order.  Bookkeeping (breaker_race refunds) runs
            # for the WHOLE completed batch first — a decisive primary
            # in the same round must not early-return past the alt's
            # refund
            round_outcomes = [(futs[f], *f.result())
                              for f in sorted(done,
                                              key=lambda f: futs[f] != idx)]
            for i, st, pl in round_outcomes:
                if (i == alt and isinstance(pl, dict)
                        and pl.get("breaker_race")):
                    # the duplicate was never actually sent (lost a
                    # half-open probe-slot race): hand both tokens back,
                    # or brownout hedging toward a half-open peer would
                    # drain the shared budget on no-ops — and give the
                    # replica back to this request's candidate set (it
                    # was never attempted; leaving it in `tried` could
                    # 503 a request whose only live peer it was)
                    self.hedge_budget.refund()
                    self.retry_budget.refund()
                    tried.discard(alt)
            for i, st, pl in round_outcomes:
                outcomes.append((i, st, pl))
                if st is not None and not _retryable(st):
                    if i == alt:
                        self._m_hedge_wins.inc()
                        if tspan is not None:
                            tspan.mark("hedge_win")
                            tspan.event(
                                "router.hedge_win",
                                replica=self._replicas[alt].endpoint.name)
                    return outcomes
        if not outcomes:
            outcomes.append((idx, None, {"error": "attempt stalled past "
                                                  "its transport timeout"}))
        return outcomes

    def _forward_predict(self, name: str, body: dict,
                         verb: str = "predict") -> Tuple[int, dict]:
        self._m_requests.inc()
        mm = self._model_stats(name, verb)
        mm.requests.inc()
        self.retry_budget.deposit()
        self.hedge_budget.deposit()
        t0 = time.perf_counter()
        rows = body.get("rows")
        # a flat 1-D body is ONE row of n_features (ServingApp reshapes
        # it), not n_features rows — miscounting it would make the
        # serving replica look features-times busier than it is
        nrows = (len(rows) if isinstance(rows, list) and rows
                 and isinstance(rows[0], (list, tuple)) else 1)
        # deadline budget: the client's deadline_ms (or the router's
        # default) pins an ABSOLUTE deadline at entry; every hop below
        # works with what remains of it
        deadline_ms = body.get("deadline_ms", None)
        if deadline_ms is None and self.default_deadline_ms > 0:
            deadline_ms = self.default_deadline_ms
        deadline_t = (None if deadline_ms is None
                      else t0 + float(deadline_ms) / 1e3)
        # trace root: minted here (or adopted from an upstream client's
        # context) and stamped with every routing decision below
        ctx = body.get(_trace.BODY_KEY)
        tspan = self.tracer.start_request(
            f"router.{verb}", ctx=ctx if isinstance(ctx, dict) else None,
            model=name, rows=nrows)
        if tspan is None:
            status, payload = self._forward_attempts(
                name, body, nrows, deadline_ms, deadline_t, t0, mm, None,
                verb)
        else:
            if deadline_ms is not None:
                tspan.set(deadline_ms=round(float(deadline_ms), 1))
            if self.policy.p99_ms and not self.tracer.keep_slo_ms:
                # without an explicit trace_keep_slo_ms, the router's own
                # SLO target is the breach line for the tail keep rule
                tspan.set(slo_ms=self.policy.p99_ms)
            try:
                with _trace.activate(tspan):
                    status, payload = self._forward_attempts(
                        name, body, nrows, deadline_ms, deadline_t, t0,
                        mm, tspan, verb)
            except BaseException as exc:
                # a request that died mid-route is exactly what tail
                # sampling exists to capture — complete its trace as the
                # 500 handle() is about to answer, then let it propagate
                tspan.finish_request(status=500, error=repr(exc))
                raise
        elapsed = time.perf_counter() - t0
        mm.window.observe(elapsed)
        mm.outcomes.observe(1.0 if status == 504 else 0.0)
        if status == 200:
            mm.latency_hist.observe(elapsed)
            mm.rows.observe(float(nrows))
        elif status == 504:
            mm.missed.inc()
        if tspan is not None:
            if isinstance(payload, dict):
                payload.setdefault("trace_id", tspan.trace_id)
            tspan.finish_request(status=status)
        return status, payload

    def _forward_attempts(self, name: str, body: dict, nrows: int,
                          deadline_ms, deadline_t: Optional[float],
                          t0: float, mm: _ModelStats,
                          tspan, verb: str = "predict") -> Tuple[int, dict]:
        attempts = 0
        candidates = self._ranked(name)
        tried: set = set()
        race_retried: set = set()
        last_err: Optional[str] = None
        degrade = bool(body.get("degrade", False))
        while candidates:
            remaining = (None if deadline_t is None
                         else deadline_t - time.perf_counter())
            if remaining is not None and remaining <= 0:
                # refuse at the router: forwarding an already-dead
                # request would spend replica admission + device time on
                # an answer nobody is waiting for
                self._m_deadline.inc()
                if tspan is not None:
                    tspan.event("router.deadline_refused",
                                attempts=attempts)
                return 504, {"error": "deadline exceeded at router "
                                      f"(budget {float(deadline_ms):g}ms, "
                                      f"attempts {attempts})"}
            if (verb == "predict" and not degrade
                    and self.cascade_mode == "deadline"
                    and remaining is not None
                    and not full_forest_affordable(
                        remaining, mm.window.percentiles()["p99_ms"])):
                # (predict-only: a degraded EXPLANATION would silently
                # attribute a different model — the prefix forest — so
                # the explain lane takes the honest 504 instead)
                # the budget is alive but (on p99 evidence) too small for
                # a full-forest answer: ask the replica for the calibrated
                # prefix instead of letting the deadline clock run out
                # into a 504.  Decided once per request — the flag rides
                # every subsequent attempt's forwarded body.
                degrade = True
                self._m_degraded.inc()
                if tspan is not None:
                    # degraded serves are always-kept by the tail sampler
                    tspan.mark("degraded")
                    tspan.event("router.degrade",
                                remaining_ms=round(remaining * 1e3, 1),
                                p99_ms=round(
                                    mm.window.percentiles()["p99_ms"], 1))
            idx = candidates[0]
            tried.add(idx)
            token_spent = False
            if attempts > 0:
                if not self.retry_budget.try_spend():
                    # brownout backpressure: no token for another attempt
                    # — an honest 503 now beats amplifying the overload
                    self._m_retry_denied.inc()
                    if tspan is not None:
                        tspan.event("router.retry_budget_exhausted",
                                    attempts=attempts)
                    return 503, {"error": "retry budget exhausted; last: "
                                          f"{last_err}"}
                token_spent = True
            attempts += 1
            if tspan is not None:
                # the routing decision, with the evidence it was made on
                rep = self._replicas[idx]
                tspan.event("router.pick", replica=rep.endpoint.name,
                            attempt=attempts, breaker=rep.breaker.state,
                            load_rows=(rep.load_rows
                                       + rep.router_inflight_rows),
                            retry_token=token_spent)
            timeout_s = (self.request_timeout_s if remaining is None
                         else min(self.request_timeout_s, remaining))
            fwd_body = body
            if remaining is not None:
                # each hop forwards the REMAINING budget, so the
                # replica's admission check (serving/batcher.py) and its
                # HTTP read timeout both derive from what is actually
                # left, not the client's original figure
                fwd_body = dict(body)
                fwd_body["deadline_ms"] = remaining * 1e3
            if degrade and not fwd_body.get("degrade"):
                if fwd_body is body:
                    fwd_body = dict(body)
                fwd_body["degrade"] = True
            outcomes = self._attempt_maybe_hedged(
                idx, name, fwd_body, nrows, timeout_s, tried, deadline_t,
                tspan, verb)
            decisive = next(
                (o for o in outcomes
                 if o[1] is not None and not _retryable(o[1])), None)
            if decisive is not None:
                served_idx, status, payload = decisive
                elapsed = time.perf_counter() - t0
                self.latency.observe(elapsed)
                self._m_latency.observe(elapsed)
                self._m_forwarded[served_idx].inc()
                if tspan is not None:
                    tspan.set(
                        replica=self._replicas[served_idx].endpoint.name,
                        attempts=attempts)
                if isinstance(payload, dict):
                    payload.setdefault(
                        "replica", self._replicas[served_idx].endpoint.name)
                    if attempts > 1:
                        payload.setdefault("rerouted", attempts - 1)
                    if served_idx != idx:
                        # served by the hedge duplicate, not a reroute —
                        # "rerouted: 0" here would be misleading noise
                        payload.setdefault("hedged", True)
                return status, payload
            for _, st, pl in outcomes:
                last_err = (pl.get("error", f"replica status {st}")
                            if isinstance(pl, dict)
                            else f"replica status {st}")
            if all(isinstance(pl, dict) and pl.get("breaker_race")
                   for _, _, pl in outcomes):
                # nothing was actually attempted (lost half-open probe
                # races): moving to the next candidate is not a retry —
                # hand the token back, don't count a reroute, and give
                # each race-lost replica ONE second chance in this
                # request's candidate set (a freed probe slot moments
                # later may be its only live peer; the once-only cap
                # keeps the loop terminating)
                if token_spent:
                    self.retry_budget.refund()
                attempts -= 1
                for i, _, pl in outcomes:
                    if pl.get("breaker_race") and i not in race_retried:
                        race_retried.add(i)
                        tried.discard(i)
            else:
                self._m_reroutes.inc()
                mm.reroutes.inc()
                if tspan is not None:
                    tspan.mark("rerouted")
                    tspan.event("router.reroute", attempt=attempts,
                                last_error=last_err)
            candidates = [i for i in self._ranked(name) if i not in tried]
        if last_err is None:
            # nothing was routable to begin with: SLO shedding
            self._m_shed.inc()
            mm.shed.inc()
            if tspan is not None:
                tspan.event("router.shed")
            self.tracer.maybe_dump("shed")
            states = self.replica_states()
            return 503, {"error": "fleet shedding load: no replica within "
                                  "SLO", "replicas": states}
        self._m_errors.inc()
        mm.errors.inc()
        return 503, {"error": f"no replica could serve the request; "
                              f"last: {last_err}"}

    def _broadcast(self, method: str, path: str, body: dict,
                   name: str, verb: str) -> Tuple[int, dict]:
        """publish/rollback fan-out: try every replica (even shed ones —
        a recovering replica must not come back serving a stale model),
        IN PARALLEL — a publish pays model load + bundle deserialize +
        warmup per replica, and a fleet-wide hot-swap should cost one
        replica's worth of wall clock, not N.  Succeeds if every
        REACHABLE replica succeeded.  A PARTIAL publish (some 200s, some
        refusals) rolls the successes back — the fleet must never
        silently serve mixed versions — and bumps
        ``lgbm_fleet_publish_partial_total``.

        Publishes ride an idempotent ``publish_token`` (minted here when
        the caller didn't supply one): a replica's registry remembers the
        token it applied and replays the same version for a duplicate, so
        (a) ``HttpReplica``'s stale-conn retry is safe for publishes,
        (b) an UNKNOWN outcome (socket timeout on a live replica — the
        publish may or may not have landed) can be RESOLVED by re-sending
        the identical request instead of being stuck unknowable, and
        (c) the rejoin replay can never double-apply to a replica that
        already has the version."""
        if verb == "publish":
            body = dict(body or {})
            if not body.get("publish_token"):
                body["publish_token"] = uuid.uuid4().hex
        # retired (scaled-down) slots take no publishes: their processes
        # are gone, and counting them unreachable would be noise
        reps = [rep for rep in self._replicas if not rep.retired]

        def _one(rep):
            try:
                status, payload = rep.endpoint.request(
                    method, path, body, timeout_s=self.request_timeout_s)
                return {"status": status, **(
                    payload if isinstance(payload, dict) else {})}
            except ReplicaTransportError as exc:
                # a socket TIMEOUT is not "unreachable": the replica is
                # alive (health polls keep passing, so it never restarts
                # and the rejoin replay never fires) and the publish may
                # still land after we stop waiting — an UNKNOWN outcome
                # that must fail the broadcast like the pool-level
                # timeout below, not be excluded from the success
                # computation.  Only a refused/reset connection (replica
                # genuinely gone; it republishes from its argv or the
                # replay cache on rejoin) is safe to exclude.
                if isinstance(exc.__cause__, TimeoutError):
                    return {"status": -1,
                            "error": f"publish outcome unknown: {exc}"}
                return {"status": 0, "error": str(exc)}

        # the persistent broadcast pool, not ad-hoc threads: its workers'
        # thread-local connections get reused across broadcasts instead
        # of leaking one fresh socket per replica per publish (and it is
        # NOT the health pool — see __init__ on starvation)
        futures = [self._bcast_pool.submit(_one, rep)
                   for rep in reps]
        results: Dict[str, Dict] = {}
        for rep, fut in zip(reps, futures):
            try:
                results[rep.endpoint.name] = fut.result(
                    self.request_timeout_s + 5.0)
            except Exception:
                # a publish that outlived its timeout has an UNKNOWN
                # outcome — that must fail the broadcast, not be
                # silently excluded from the success computation
                results[rep.endpoint.name] = {
                    "status": -1,
                    "error": "publish still in flight (timed out)"}
        if verb == "publish":
            # UNKNOWN-outcome resolution: a timed-out publish on a live
            # replica may or may not have landed.  The token makes the
            # identical re-send safe either way (already landed → the
            # registry replays the same version; never landed → it
            # applies now), so one resolution round turns most UNKNOWNs
            # into a definite success/refusal; a replica that times out
            # AGAIN stays -1 and fails the broadcast as before.
            unknown = [rep for rep in reps
                       if results[rep.endpoint.name]["status"] == -1]
            if unknown:
                log_warning(
                    f"fleet: publish of {name!r} has {len(unknown)} "
                    f"unknown outcome(s); re-sending idempotently to "
                    f"resolve")
                # fresh threads, NOT the broadcast pool: round one's
                # workers may still be wedged on the very sends being
                # resolved (a slow-dripping replica holds its worker up
                # to ~2x request_timeout_s), and a resolution queued
                # behind them would time out without ever starting.
                # Rare path (partial publishes), so ad-hoc threads over
                # pooled connections are fine
                resolved_map: Dict[str, Dict] = {}

                def _resolve(rep):
                    resolved_map[rep.endpoint.name] = _one(rep)

                threads = [threading.Thread(target=_resolve, args=(rep,),
                                            daemon=True,
                                            name="lgbm-tpu-fleet-resolve")
                           for rep in unknown]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(2.0 * self.request_timeout_s + 10.0)
                for rep in unknown:
                    resolved = resolved_map.get(rep.endpoint.name)
                    if resolved is not None and resolved["status"] != -1:
                        resolved["resolved_by_token_resend"] = True
                        results[rep.endpoint.name] = resolved
        ok = sum(r["status"] == 200 for r in results.values())
        reachable = [r for r in results.values() if r["status"] != 0]
        all_ok = bool(reachable) and all(r["status"] == 200
                                         for r in reachable)
        if verb == "publish" and not all_ok and ok > 0:
            # PARTIAL publish: some replicas installed the new version,
            # others refused (or their outcome is unknown).  Leaving it be
            # would silently serve MIXED versions behind one front door —
            # the worst failure mode, because every response looks
            # healthy.  Roll the confirmed successes back so the fleet
            # converges on the old version; replicas with UNKNOWN
            # outcomes (status -1 timeouts) are deliberately NOT rolled
            # back — a rollback on a replica whose publish never landed
            # would withdraw its previous GOOD version instead.
            self._m_publish_partial.inc()
            self.tracer.maybe_dump("publish_partial")
            base_path = path[:path.rfind(":")]
            to_undo = [rep for rep in reps
                       if results[rep.endpoint.name]["status"] == 200]
            log_warning(
                f"fleet: partial publish of {name!r} ({ok}/"
                f"{len(reps)} replicas) — rolling back the "
                f"{len(to_undo)} that succeeded")

            def _undo(rep):
                # a replica whose FIRST version of this model just
                # landed (publish returned version 1) has no previous to
                # roll back to — its undo is :unpublish, restoring the
                # nothing-published state the refusing replicas are in
                first = results[rep.endpoint.name].get("version") == 1
                undo_path = base_path + (":unpublish" if first
                                         else ":rollback")
                try:
                    status, _ = rep.endpoint.request(
                        "POST", undo_path, None,
                        timeout_s=self.request_timeout_s)
                    return status
                except ReplicaTransportError as exc:
                    log_warning(f"fleet: rollback of partial publish on "
                                f"{rep.endpoint.name} failed: {exc}")
                    return 0
            undo_futs = [self._bcast_pool.submit(_undo, rep)
                         for rep in to_undo]
            for rep, fut in zip(to_undo, undo_futs):
                try:
                    status = fut.result(self.request_timeout_s + 5.0)
                except Exception:
                    status = 0
                results[rep.endpoint.name]["rolled_back"] = status == 200
                if status != 200:
                    # still mixed: say so loudly — the operator's signal
                    # is the partial counter plus this per-replica flag
                    log_warning(
                        f"fleet: replica {rep.endpoint.name} may still "
                        f"serve the withdrawn version of {name!r} "
                        f"(rollback status {status})")
        if all_ok:
            # maintain the rejoin-replay cache: a fleet-wide publish is
            # remembered (replayed to replicas that restart with their
            # original models), and a fleet-wide ROLLBACK withdraws the
            # memory — replaying a rolled-back publish to a rejoining
            # replica would resurrect the withdrawn version on one
            # replica only
            if verb == "publish":
                versions = [r.get("version") for r in results.values()
                            if r["status"] == 200
                            and isinstance(r.get("version"), int)]
                with self._lock:
                    self._published[name] = dict(body)
                    if versions:
                        self._model_versions[name] = max(versions)
            elif verb == "rollback":
                with self._lock:
                    self._published.pop(name, None)
                    versions = [r.get("version") for r in results.values()
                                if r["status"] == 200
                                and isinstance(r.get("version"), int)]
                    if versions:
                        self._model_versions[name] = max(versions)
        return (200 if all_ok else 502), {"replicas": results,
                                          "succeeded": ok}

    # ------------------------------------------------------------------
    # Placement + scale API (consumed by fleet/placement/): the router
    # owns the model->replica table; the controller computes it and the
    # autoscaler grows/shrinks the replica set under it.
    # ------------------------------------------------------------------
    def live_indices(self) -> List[int]:
        """Non-retired replica slots (routable or not)."""
        with self._lock:
            return [i for i, rep in enumerate(self._replicas)
                    if not rep.retired]

    def placement(self, name: str) -> set:
        """Replica indices currently hosting ``name``: the table entry,
        or every live slot for an unplaced (broadcast-published) model."""
        with self._lock:
            placed = self._placement.get(name)
            if placed is not None:
                return set(placed)
            return {i for i, rep in enumerate(self._replicas)
                    if not rep.retired}

    def set_placement(self, name: str, indices) -> None:
        """Atomically flip ``name``'s model->replica table entry (one
        dict store under the lock — requests in flight either see the
        old set or the new one, never a partial).  ``None`` clears the
        entry, restoring fleet-wide routing."""
        with self._lock:
            if indices is None:
                self._placement.pop(name, None)
            else:
                self._placement[name] = frozenset(int(i) for i in indices)

    def note_version(self, name: str, version: int) -> None:
        """Record a fleet-confirmed version (controller moves maintain
        the same column broadcast publishes do)."""
        with self._lock:
            self._model_versions[name] = max(
                int(version), self._model_versions.get(name, 0))

    def published_body(self, name: str) -> Optional[dict]:
        """The last fleet-confirmed publish body for ``name`` — what a
        targeted (per-replica) re-publish must send so the destination
        installs the same model the fleet serves."""
        with self._lock:
            body = self._published.get(name)
            return dict(body) if body is not None else None

    def add_replica(self, endpoint) -> int:
        """Register a scaled-up replica slot and return its index.  Every
        index-parallel structure (per-replica metric lists, SLO/breaker
        records) grows together under the lock; the new slot starts
        optimistically routable, same as launch-time replicas."""
        reg = self.registry
        with self._lock:
            idx = len(self._replicas)
            rep = _Replica(endpoint, ReplicaSLO(self.policy),
                           CircuitBreaker(**self._breaker_args),
                           LatencyDigest())
            self._replicas.append(rep)
            self._m_forwarded.append(reg.counter(
                "lgbm_fleet_forwarded_total", "predicts forwarded",
                replica=endpoint.name))
            self._m_up.append(reg.gauge(
                "lgbm_fleet_replica_up",
                "1 routable / 0 shed or down", replica=endpoint.name))
            self._m_load.append(reg.gauge(
                "lgbm_fleet_replica_load_rows",
                "queued+in-flight rows at last poll",
                replica=endpoint.name))
            self._m_p99.append(reg.gauge(
                "lgbm_fleet_replica_p99_ms", "replica p99 at last poll",
                replica=endpoint.name))
            self._m_fill.append(reg.gauge(
                "lgbm_fleet_replica_batch_fill",
                "replica in-flight batch fill at last poll",
                replica=endpoint.name))
            self._m_breaker.append(reg.gauge(
                "lgbm_fleet_replica_breaker_state",
                "data-path circuit breaker: 0 closed / 1 half-open / 2 "
                "open", replica=endpoint.name))
            self._m_up[idx].set(1)
            return idx

    def retire_replica(self, idx: int) -> None:
        """Take slot ``idx`` permanently out of rotation (scale-down).
        The slot is flagged, not removed — indices stay stable — and it
        is stripped from every placement entry so placement() snapshots
        stay truthful.  The caller is responsible for having moved the
        slot's placed models elsewhere first (drain-before-retire)."""
        with self._lock:
            rep = self._replicas[idx]
            rep.retired = True
            self._m_up[idx].set(0)
            for name, placed in list(self._placement.items()):
                if idx in placed:
                    self._placement[name] = placed - {idx}
        log_info(f"fleet: replica {rep.endpoint.name} retired "
                 f"(scale-down)")

    def model_table(self) -> Dict[str, Dict]:
        """GET /v1/fleet/models: per-model placement row — replica set,
        fleet-confirmed version, and the SLO gauge snapshot the placement
        controller feeds on."""
        with self._lock:
            # verb-suffixed stats rows (``name:explain``) are metric
            # lanes, not models — they must not mint phantom table rows
            names = (set(self._published) | set(self._placement)
                     | set(self._model_versions)
                     | ({k for k in self._per_model if ":" not in k}
                        - {"_other"}))
            out: Dict[str, Dict] = {}
            for name in sorted(names):
                placed = self._placement.get(name)
                idxs = (sorted(placed) if placed is not None
                        else [i for i, rep in enumerate(self._replicas)
                              if not rep.retired])
                mm = self._per_model.get(name)
                row = {
                    "replicas": [self._replicas[i].endpoint.name
                                 for i in idxs],
                    "placed": placed is not None,
                    "version": self._model_versions.get(name),
                }
                if mm is not None:
                    n = mm.outcomes.window_count()
                    row["slo"] = {
                        "p99_ms": mm.window.percentiles()["p99_ms"],
                        "deadline_miss_ratio": (
                            mm.outcomes.window_sum() / n if n else 0.0),
                        "goodput_rows_per_s": (
                            mm.rows.window_sum()
                            / (mm.rows.window_s or 1.0)),
                    }
                out[name] = row
            return out

    # ------------------------------------------------------------------
    def _trace_detail(self, trace_id: str) -> Tuple[int, dict]:
        """Cross-process trace assembly on demand: this router's own
        spans for ``trace_id`` merged with every replica's
        (``GET /v1/trace/<id>`` fan-out against their flight-recorder
        rings) — the full causal chain of one request, hop by hop.
        Unreachable replicas are skipped; a trace nobody remembers is a
        404."""
        own = self.tracer.recorder.get(trace_id)
        spans: List[dict] = list(own.get("spans", [])) if own else []
        processes = 1 if own else 0
        timeout_s = max(self.health_timeout_s, 1.0)

        def _one(rep):
            # best-effort: a down/faked replica contributes nothing
            try:
                return rep.endpoint.request(
                    "GET", f"/v1/trace/{trace_id}", None,
                    timeout_s=timeout_s)
            except Exception:
                return None, None

        # parallel fan-out on the broadcast pool (same rationale as
        # _broadcast): several unreachable replicas queried serially
        # would stall this debug route by N x timeout exactly during the
        # incident it exists for
        futures = [self._bcast_pool.submit(_one, rep)
                   for rep in self._replicas]
        for fut in futures:
            try:
                status, payload = fut.result(timeout_s + 5.0)
            except Exception:
                continue
            if status == 200 and isinstance(payload, dict):
                spans.extend(payload.get("spans") or [])
                processes += 1
        if not spans:
            return 404, {"error": f"no trace {trace_id!r} in any flight "
                                  "recorder"}
        spans.sort(key=lambda s: (float(s.get("start_unix_s", 0.0)),
                                  str(s.get("span_id", ""))))
        out = {"trace_id": trace_id, "processes": processes,
               "spans": spans}
        if own is not None:
            out["status"] = own.get("status")
            out["kept"] = own.get("kept")
            out["keep"] = own.get("keep")
            out["dur_ms"] = own.get("dur_ms")
        return 200, out

    def replica_states(self) -> Dict[str, Dict]:
        sup = self.supervisor
        with self._lock:
            out = {}
            for i, rep in enumerate(self._replicas):
                p50 = rep.digest.quantile(0.5)
                entry = {
                    "state": "retired" if rep.retired else rep.slo.state,
                    "load_rows": rep.load_rows,
                    "reasons": list(rep.slo.last_reasons),
                    "transitions": rep.slo.transitions,
                    "breaker": rep.breaker.snapshot(),
                    "latency_p50_ms": (None if p50 is None
                                       else round(p50 * 1e3, 3)),
                    "queue_wait_ms": round(rep.queue_wait_ms, 3),
                }
                if sup is not None and i < len(sup.replicas):
                    # supervision visibility: an abandoned slot (restart
                    # budget spent) looks identical to plain "down" from
                    # the routing side, but an operator must see the
                    # difference — down heals itself, abandoned never
                    entry["abandoned"] = bool(sup.replicas[i].gave_up)
                    entry["restarts"] = int(sup.replicas[i].restarts)
                out[rep.endpoint.name] = entry
            return out

    def handle(self, method: str, path: str,
               body: Optional[dict] = None) -> Tuple[int, dict]:
        """Transport-free request handler, ServingApp.handle-compatible."""
        try:
            return self._route(method.upper(), path.rstrip("/") or "/",
                               body or {})
        except ReplicaTransportError as exc:
            return 502, {"error": str(exc)}
        except LightGBMError as exc:
            return 400, {"error": str(exc)}
        except (KeyError, ValueError, TypeError) as exc:
            return 400, {"error": f"{type(exc).__name__}: {exc}"}
        except Exception as exc:
            # same contract as ServingApp.handle: an escaped exception
            # tears the connection down, which an upstream load balancer
            # cannot distinguish from a dead router — always answer
            log_warning(f"fleet: unhandled router error for {method} "
                        f"{path}: {exc!r}")
            return 500, {"error": f"internal: {type(exc).__name__}: {exc}"}

    def _route(self, method: str, path: str, body: dict) -> Tuple[int, dict]:
        if self._closed:
            return 503, {"error": "router is closed"}
        if method == "GET" and path == "/healthz":
            states = self.replica_states()
            routable = sum(s["state"] == "healthy" for s in states.values())
            return 200, {"status": "ok" if routable else "shedding",
                         "role": "router", "routable": routable,
                         "replicas": states}
        if method == "GET" and path == "/v1/fleet/replicas":
            return 200, {"replicas": self.replica_states()}
        if method == "GET" and path == "/v1/fleet/models":
            return 200, {"models": self.model_table()}
        if method == "GET" and path == "/v1/metrics":
            self.refresh_model_gauges()
            out = {"router": self.registry.snapshot(),
                   "replicas": self.replica_states()}
            out["router"]["p_ms"] = self.latency.percentiles()
            return 200, out
        if method == "GET" and path == "/v1/metrics/prometheus":
            from ..telemetry import prometheus_text
            self.refresh_model_gauges()
            return 200, prometheus_text(self.registry)
        if method == "GET" and path == "/v1/trace/recent":
            return 200, {"traces": self.tracer.recorder.recent()}
        if method == "GET" and path.startswith("/v1/trace/"):
            return self._trace_detail(path[len("/v1/trace/"):])
        if method == "GET" and path == "/v1/models":
            for idx in self._ranked():
                try:
                    return self._replicas[idx].endpoint.request(
                        "GET", path, None, timeout_s=self.request_timeout_s)
                except ReplicaTransportError as exc:
                    self._mark_down(idx, str(exc))
            return 503, {"error": "no routable replica"}
        if (method == "POST" and path.startswith("/v1/models/")
                and path.endswith("/explain") and ":" not in path):
            # REST-style alias, mirroring the replica's own route
            name = path[len("/v1/models/"):-len("/explain")]
            if name:
                return self._forward_predict(name, body, verb="explain")
        if (method == "POST" and path.startswith("/v1/models/")
                and path.endswith("/rank") and ":" not in path):
            # REST-style alias, mirroring the replica's own route
            name = path[len("/v1/models/"):-len("/rank")]
            if name:
                return self._forward_predict(name, body, verb="rank")
        if path.startswith("/v1/models/") and ":" in path and method == "POST":
            rest = path[len("/v1/models/"):]
            name, _, verb = rest.rpartition(":")
            if name and verb == "predict":
                return self._forward_predict(name, body)
            if name and verb == "explain":
                return self._forward_predict(name, body, verb="explain")
            if name and verb == "rank":
                return self._forward_predict(name, body, verb="rank")
            if name and verb in ("publish", "rollback"):
                return self._broadcast(method, path, body, name, verb)
        return 404, {"error": f"no route for {method} {path}"}
