"""Fleet serving tier: router + N replica workers + supervision.

PR 1 built a single-process inference server; this package turns it into
a topology that plausibly fronts heavy traffic:

- ``FleetRouter`` (router.py) — one front door routing each predict to
  the least-loaded replica, rerouting around failures, shedding at the
  door when no replica is within SLO, and broadcasting publish/rollback
  fleet-wide.  Transport-free ``handle`` contract, ServingApp-compatible.
- ``SLOPolicy`` / ``ReplicaSLO`` (slo.py) — the per-replica
  breach→shed→recover state machine fed by each replica's telemetry
  gauges (p99, queue depth, in-flight batch fill).
- ``FleetSupervisor`` (supervisor.py) — spawns one serving process per
  replica, restarts the dead ones with bounded backoff (fault env
  stripped, cluster.py-style), each replica cold-starting warm from the
  shared AOT bundle.

CLI: ``task=serve fleet_replicas=N`` launches the whole fleet
(replicas on ``fleet_base_port..+N-1``, router on ``serving_port``);
``task=serve fleet_role=router fleet_replica_urls=...`` runs just a
router over externally managed replicas; ``fleet_role=replica`` is the
single-process server (what the supervisor spawns).
"""

from __future__ import annotations

from typing import Optional

from .breaker import CircuitBreaker, LatencyDigest, RetryBudget
from .chaosnet import ChaosReplica
from .placement import FleetAutoscaler, PlacementController
from .router import FleetRouter, HttpReplica, ReplicaTransportError
from .slo import DOWN, HEALTHY, SHED, ReplicaSLO, SLOPolicy
from .supervisor import FleetSupervisor, default_replica_argv

__all__ = ["FleetRouter", "HttpReplica", "ReplicaTransportError",
           "SLOPolicy", "ReplicaSLO", "HEALTHY", "SHED", "DOWN",
           "CircuitBreaker", "LatencyDigest", "RetryBudget",
           "ChaosReplica", "FleetSupervisor", "default_replica_argv",
           "PlacementController", "FleetAutoscaler",
           "placement_from_config", "autoscaler_from_config",
           "policy_from_config", "serve_fleet", "serve_router"]


def policy_from_config(config) -> SLOPolicy:
    return SLOPolicy(p99_ms=config.fleet_slo_p99_ms,
                     queue_rows=config.fleet_slo_queue_rows,
                     breach_polls=config.fleet_breach_polls,
                     recover_polls=config.fleet_recover_polls)


def _make_router(config, urls, registry=None, supervisor=None) -> FleetRouter:
    return FleetRouter([HttpReplica(u) for u in urls],
                       policy=policy_from_config(config),
                       poll_interval_ms=config.fleet_poll_ms,
                       registry=registry,
                       supervisor=supervisor,
                       hedge_quantile=config.fleet_hedge_quantile,
                       hedge_min_ms=config.fleet_hedge_min_ms,
                       hedge_budget_pct=config.fleet_hedge_budget_pct,
                       retry_budget_pct=config.fleet_retry_budget_pct,
                       breaker_failures=config.fleet_breaker_failures,
                       breaker_cooldown_s=config.fleet_breaker_cooldown_s,
                       breaker_probes=config.fleet_breaker_probes,
                       latency_routing=bool(config.fleet_latency_routing),
                       default_deadline_ms=config.fleet_deadline_ms,
                       cascade_mode=getattr(config, "cascade_mode", "off"))


def placement_from_config(config, router) -> PlacementController:
    return PlacementController(
        router,
        max_models_per_replica=config.fleet_max_models_per_replica,
        headroom=config.fleet_placement_headroom,
        capacity_rows_s=config.fleet_placement_capacity_rows_s,
        spread_rows_s=config.fleet_placement_spread_rows_s,
        drain_ms=config.fleet_placement_drain_ms,
        poll_ms=config.fleet_placement_poll_ms)


def autoscaler_from_config(config, supervisor, router,
                           controller=None) -> FleetAutoscaler:
    return FleetAutoscaler(
        supervisor, router, controller=controller,
        min_replicas=config.fleet_autoscale_min_replicas,
        max_replicas=config.fleet_autoscale_max_replicas,
        miss_ratio_high=config.fleet_autoscale_miss_ratio,
        capacity_rows_s=config.fleet_placement_capacity_rows_s,
        headroom=config.fleet_placement_headroom,
        polls=config.fleet_autoscale_polls,
        cooldown_s=config.fleet_autoscale_cooldown_s,
        ready_timeout_s=config.fleet_ready_timeout_s)


def serve_router(config, urls: Optional[list] = None) -> None:
    """Blocking router over externally managed replicas
    (task=serve fleet_role=router fleet_replica_urls=host:p1,host:p2)."""
    from ..log import LightGBMError
    from ..serving.server import serve
    urls = urls if urls is not None else [
        u for u in str(config.fleet_replica_urls).split(",") if u.strip()]
    if not urls:
        raise LightGBMError(
            "fleet_role=router requires fleet_replica_urls=host:port,...")
    router = _make_router(config, urls)
    serve(router, host=config.serving_host, port=config.serving_port)


def serve_fleet(raw_params: dict, config) -> None:
    """Blocking full-fleet launch: spawn fleet_replicas serving processes
    (supervised, warm from the shared AOT bundle), then run the router in
    THIS process on serving_port."""
    import signal

    from ..cluster import find_open_ports
    from ..log import log_info
    from ..serving.server import serve
    # SIGTERM's default action skips every finally: the launcher dies and
    # ORPHANS its replica processes.  Convert it to a normal unwind so
    # serve()'s cleanup and stop_all() below run (SIGINT already raises).
    def _terminate(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _terminate)
    n = int(config.fleet_replicas)
    if config.fleet_base_port > 0:
        ports = [config.fleet_base_port + i for i in range(n)]
    else:
        ports = find_open_ports(n, host=config.serving_host)
    from ..telemetry.registry import MetricsRegistry
    registry = MetricsRegistry()   # shared: router gauges + supervisor
    sup = FleetSupervisor(
        lambda idx, port: default_replica_argv(raw_params, port),
        ports, host=config.serving_host,
        max_restarts=config.fleet_max_restarts,
        restart_backoff_s=config.fleet_restart_backoff_s,
        metrics_registry=registry)
    controller = autoscaler = None
    try:
        sup.spawn_all()
        sup.wait_ready(timeout_s=config.fleet_ready_timeout_s)
        sup.start_watching()
        router = _make_router(config, sup.urls, registry=registry,
                              supervisor=sup)
        if config.fleet_placement:
            controller = placement_from_config(config, router).start()
        if config.fleet_autoscale_max_replicas > 0:
            autoscaler = autoscaler_from_config(
                config, sup, router, controller=controller).start()
        log_info(f"fleet: {n} replicas ready on ports {ports}; router on "
                 f"http://{config.serving_host}:{config.serving_port}")
        serve(router, host=config.serving_host, port=config.serving_port)
    finally:
        if autoscaler is not None:
            autoscaler.close()
        if controller is not None:
            controller.close()
        sup.stop_all()
