"""``chaosnet`` — a fault-injecting replica transport for gray-failure
tests (the network analog of ``io/chaos.py``'s ``chaosio://``).

``ChaosReplica`` wraps any replica endpoint (``HttpReplica`` or a test
fake: anything with ``name``/``request``/``health``) and injects the
request-path failures the fleet tier claims to survive:

- **latency** (``add_latency``): every data-path request sleeps first —
  the gray replica.  Health polls are untouched by default
  (``affect_health=False``), which is exactly what makes the failure
  gray: the replica keeps passing polls while its data path crawls.
- **black holes** (``black_hole``): the next N data requests consume the
  caller's full timeout and then die with a timeout-caused
  ``ReplicaTransportError`` — packets leaving and never returning.
- **slow drips** (``slow_drip``): the next N requests are delivered to
  the replica and then the *response* stalls — the request LANDED, the
  caller just can't know it did.  This is the publish UNKNOWN-outcome
  case the idempotent publish token exists for.
- **connection resets** (``reset_next``): the next N requests fail
  immediately with a reset-flavored ``ReplicaTransportError``.

All faults apply to ``request``; ``health`` delegates untouched unless
``affect_health=True``.  Per-fault fired counters mirror ``ChaosScheme``
so a chaos test can assert each fault actually fired instead of passing
vacuously, and ``sleep_fn`` is injectable so unit tests pay no
wall-clock.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..log import LightGBMError
from .router import ReplicaTransportError

__all__ = ["ChaosReplica"]


class ChaosReplica:
    """Armable fault wrapper around one replica endpoint."""

    def __init__(self, endpoint, affect_health: bool = False,
                 sleep_fn: Callable[[float], None] = time.sleep):
        if not hasattr(endpoint, "request"):
            raise LightGBMError(
                "ChaosReplica wraps a replica endpoint (needs .request)")
        self.endpoint = endpoint
        self.name = getattr(endpoint, "name", "chaos")
        self.affect_health = bool(affect_health)
        self._sleep = sleep_fn
        self._lock = threading.Lock()
        self._latency_s = 0.0
        self._black_holes = 0
        self._black_hole_cap_s = 30.0
        self._slow_drips = 0
        self._drip_s = 0.0
        self._resets = 0
        self.counters: Dict[str, int] = {
            "requests": 0, "latency_injections": 0, "latency_timeouts": 0,
            "black_holes": 0, "slow_drips": 0, "resets": 0,
        }

    # -- arming -----------------------------------------------------------
    def add_latency(self, seconds: float) -> None:
        """Every data-path request sleeps this long first (0 disarms)."""
        with self._lock:
            self._latency_s = float(seconds)

    def black_hole(self, n: int = 1, cap_s: float = 30.0) -> None:
        """Next N data requests eat the caller's timeout, then die with a
        timeout-caused transport error (the request never arrived)."""
        with self._lock:
            self._black_holes = int(n)
            self._black_hole_cap_s = float(cap_s)

    def slow_drip(self, n: int = 1, delay_s: float = 1.0) -> None:
        """Next N requests REACH the replica, then the response stalls
        delay_s — the caller may time out on an op that landed."""
        with self._lock:
            self._slow_drips = int(n)
            self._drip_s = float(delay_s)

    def reset_next(self, n: int = 1) -> None:
        """Next N data requests fail instantly with a connection reset."""
        with self._lock:
            self._resets = int(n)

    def calm(self) -> None:
        """Disarm everything (tests' teardown / soak recovery phase)."""
        with self._lock:
            self._latency_s = 0.0
            self._black_holes = self._slow_drips = self._resets = 0

    # -- endpoint interface ----------------------------------------------
    def invalidate_pool(self) -> None:
        invalidate = getattr(self.endpoint, "invalidate_pool", None)
        if invalidate is not None:
            invalidate()

    def health(self, timeout_s: float = 2.0) -> Optional[Dict]:
        if self.affect_health:
            try:
                self._apply_pre_faults(timeout_s)
            except ReplicaTransportError:
                return None
        return self.endpoint.health(timeout_s)

    def _apply_pre_faults(self, timeout_s: Optional[float]) -> None:
        """Faults that fire BEFORE the request reaches the replica."""
        with self._lock:
            self.counters["requests"] += 1
            reset = self._resets > 0
            if reset:
                self._resets -= 1
                self.counters["resets"] += 1
            hole = (not reset) and self._black_holes > 0
            if hole:
                self._black_holes -= 1
                self.counters["black_holes"] += 1
                hole_s = min(timeout_s or self._black_hole_cap_s,
                             self._black_hole_cap_s)
            latency = self._latency_s
        if reset:
            raise ReplicaTransportError(
                f"replica {self.name}: chaosnet connection reset"
            ) from ConnectionResetError("chaosnet reset")
        if hole:
            self._sleep(hole_s)
            raise ReplicaTransportError(
                f"replica {self.name}: chaosnet black hole "
                f"(timed out after {hole_s:g}s)") from TimeoutError(
                    "chaosnet black hole")
        if latency > 0:
            with self._lock:
                self.counters["latency_injections"] += 1
            if timeout_s is not None and latency >= timeout_s:
                # fidelity with a real slow network: the caller's read
                # timeout fires at timeout_s — it does NOT wait out the
                # injected latency and then get a late answer (which
                # would hand deadline-squeezed requests 200s a real
                # socket could never deliver)
                self._sleep(timeout_s)
                with self._lock:
                    self.counters["latency_timeouts"] += 1
                raise ReplicaTransportError(
                    f"replica {self.name}: chaosnet latency "
                    f"({latency:g}s) exceeded timeout {timeout_s:g}s"
                ) from TimeoutError("chaosnet latency")
            self._sleep(latency)

    def request(self, method: str, path: str, body: Optional[dict] = None,
                timeout_s: Optional[float] = None) -> Tuple[int, dict]:
        self._apply_pre_faults(timeout_s)
        out = self.endpoint.request(method, path, body, timeout_s=timeout_s)
        with self._lock:
            drip = self._slow_drips > 0
            if drip:
                self._slow_drips -= 1
                self.counters["slow_drips"] += 1
            drip_s = self._drip_s
        if drip:
            # the request LANDED; only the response is late.  When the
            # drip outlives the caller's timeout, surface the same
            # timeout-caused transport error a real stalled socket would
            # — the op's outcome is genuinely unknown to the caller.
            if timeout_s is not None and drip_s >= timeout_s:
                self._sleep(timeout_s)
                raise ReplicaTransportError(
                    f"replica {self.name}: chaosnet slow drip "
                    f"(response stalled past {timeout_s:g}s)"
                ) from TimeoutError("chaosnet slow drip")
            self._sleep(drip_s)
        return out
