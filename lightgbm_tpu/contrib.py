"""SHAP feature contributions, device-side.

TPU-native equivalent of the reference's recursive TreeSHAP
(Tree::PredictContrib, include/LightGBM/tree.h:139; TreeSHAP recursion in
src/io/tree.cpp).  The recursion does not vectorize, so this uses the
per-leaf decomposition (the same reformulation GPUTreeShap uses): for a leaf
l with unique path features U, per row x,

    phi_i += v_l * (o_i - z_i) * sum_k c_k(i) * k! (u-1-k)! / u!

where o_j = 1 iff x satisfies ALL of feature j's splits on the path,
z_j = product of child-cover fractions of feature j's splits, and c_k(i) are
the coefficients of prod_{j in U\\{i}} (z_j + o_j t).  Host code precomputes
the per-leaf path tables once per model; the device evaluates all
(row, leaf, feature) terms with fixed-shape scans — O(L * D^2) per row.

Output layout matches the reference: per-class blocks of [F feature columns
+ bias column], bias = expected value, each row's block summing to the raw
prediction.
"""

from __future__ import annotations

import functools
import math
from typing import List, NamedTuple

import numpy as np
import jax

__all__ = ["predict_contrib"]

_K_ZERO = 1e-35
_EPS = 1e-12


class _TreePaths(NamedTuple):
    """Per-tree path tables (one leaf per row, padded to max depth D)."""
    step_node: np.ndarray     # [L, D] int32 internal node id (-1 pad)
    step_dir: np.ndarray      # [L, D] bool: path goes LEFT at this node
    slot_of_step: np.ndarray  # [L, D] int32: unique-feature slot of step
    slot_feat: np.ndarray     # [L, D] int32 real feature id (-1 pad)
    slot_z: np.ndarray        # [L, D] f64 cover-fraction product (1.0 pad)
    n_slots: np.ndarray       # [L] int32 (u per leaf)
    leaf_value: np.ndarray    # [L]
    expected: float           # E[f] = sum_l v_l * prod(path covers)


def _tree_paths(tree) -> _TreePaths:
    nl = tree.num_leaves
    if nl <= 1:
        return _TreePaths(np.full((1, 1), -1, np.int32),
                          np.zeros((1, 1), bool),
                          np.zeros((1, 1), np.int32),
                          np.full((1, 1), -1, np.int32),
                          np.ones((1, 1)),
                          np.zeros(1, np.int32),
                          np.asarray([tree.leaf_value[0]]),
                          float(tree.leaf_value[0]))
    paths = []  # per leaf: list of (node, went_left, cover_frac)
    weights = tree.internal_weight
    lweights = tree.leaf_weight
    counts = tree.internal_count
    lcounts = tree.leaf_count

    def node_weight(code):
        if code >= 0:
            w = weights[code]
            return w if w > 0 else float(counts[code])
        leaf = ~code
        w = lweights[leaf]
        return w if w > 0 else float(lcounts[leaf])

    def walk(code, path):
        if code < 0:
            paths.append((~code, list(path)))
            return
        w = node_weight(code)
        for child, went_left in ((tree.left_child[code], True),
                                 (tree.right_child[code], False)):
            frac = node_weight(child) / max(w, _EPS)
            path.append((code, went_left, frac))
            walk(child, path)
            path.pop()

    walk(0, [])
    paths.sort(key=lambda p: p[0])
    D = max(1, max(len(p) for _, p in paths))
    L = nl
    step_node = np.full((L, D), -1, np.int32)
    step_dir = np.zeros((L, D), bool)
    slot_of_step = np.zeros((L, D), np.int32)
    slot_feat = np.full((L, D), -1, np.int32)
    slot_z = np.ones((L, D))
    n_slots = np.zeros(L, np.int32)
    leaf_value = np.zeros(L)
    expected = 0.0
    for leaf, path in paths:
        leaf_value[leaf] = tree.leaf_value[leaf]
        cover = 1.0
        slots = {}
        for s, (node, went_left, frac) in enumerate(path):
            cover *= frac
            feat = int(tree.split_feature[node])
            if feat not in slots:
                slots[feat] = len(slots)
            j = slots[feat]
            step_node[leaf, s] = node
            step_dir[leaf, s] = went_left
            slot_of_step[leaf, s] = j
            slot_feat[leaf, j] = feat
            slot_z[leaf, j] *= frac
        n_slots[leaf] = len(slots)
        expected += tree.leaf_value[leaf] * cover
    return _TreePaths(step_node, step_dir, slot_of_step, slot_feat, slot_z,
                      n_slots, leaf_value, float(expected))


def _go_left_matrix(tree, X: np.ndarray) -> np.ndarray:
    """[N, M] bool: would row go left at each internal node (same decision
    semantics as ops/predict._traverse_one_tree)."""
    ni = max(tree.num_leaves - 1, 1)
    n = X.shape[0]
    out = np.zeros((n, ni), bool)
    for node in range(tree.num_leaves - 1):
        fval = X[:, tree.split_feature[node]]
        d = int(tree.decision_type[node])
        missing_type = (d >> 2) & 3
        default_left = (d & 2) != 0
        isnan = np.isnan(fval)
        if d & 1:  # categorical
            ival = np.where(isnan, -1, fval).astype(np.int64)
            cat_idx = int(tree.threshold[node])
            lo = tree.cat_boundaries[cat_idx]
            hi = tree.cat_boundaries[cat_idx + 1]
            words = np.asarray(tree.cat_threshold[lo:hi], np.uint32)
            word = ival >> 5
            ok = (ival >= 0) & (word < (hi - lo))
            wv = words[np.clip(word, 0, hi - lo - 1)]
            out[:, node] = ok & (((wv >> (ival & 31)) & 1) == 1)
        else:
            fv = np.where(isnan & (missing_type != 2), 0.0, fval)
            iszero = np.abs(fv) < _K_ZERO
            is_missing = ((missing_type == 2) & isnan) | \
                         ((missing_type == 1) & iszero)
            out[:, node] = np.where(is_missing, default_left,
                                    fv <= tree.threshold[node])
    return out


@functools.partial(jax.jit, static_argnames=("num_features",))
def _tree_contrib(go_left, step_node, step_dir, slot_of_step, slot_feat,
                  slot_z, n_slots, leaf_value, fact_w, num_features: int):
    """phi [N, F+1] for one tree given the row decisions at each node.

    The per-tree dispatch shape of ``explain.paths.tree_phi`` (the one
    implementation of the per-leaf math) — kept as the bit-reference the
    batched host path's regression test compares against."""
    from .explain.paths import tree_phi
    return tree_phi(go_left, step_node, step_dir, slot_of_step, slot_feat,
                    slot_z, n_slots, leaf_value, fact_w,
                    num_features=num_features)


def _fact_weights(D: int) -> np.ndarray:
    """[u, k] -> k! (u-1-k)! / u! lookup (0 where k >= u)."""
    w = np.zeros((D + 1, D + 1))
    for u in range(1, D + 1):
        for k in range(u):
            w[u, k] = (math.factorial(k) * math.factorial(u - 1 - k)
                       / math.factorial(u))
    return w


def predict_contrib(trees: List, X: np.ndarray, num_class: int) -> np.ndarray:
    """[N, (F+1) * num_class] SHAP values (reference PredictContrib layout:
    per-class blocks of F feature columns + bias column)."""
    X = np.ascontiguousarray(np.asarray(X, np.float64))
    n, f = X.shape
    out = np.zeros((n, (f + 1) * num_class))
    if not trees:
        return out
    # ONE scanned device dispatch for all trees (go-left decisions stay
    # host f64) instead of a Python re-dispatch per tree; the f64 class
    # accumulation below keeps the per-tree order, so the output is
    # bit-identical to the legacy loop over _tree_contrib
    from .explain.paths import forest_phi_host
    phi_all, expected = forest_phi_host(trees, X, f)
    for i, tree in enumerate(trees):
        cls = i % num_class
        lo = cls * (f + 1)
        if tree.num_leaves <= 1:
            out[:, lo + f] += tree.leaf_value[0]
            continue
        out[:, lo:lo + f + 1] += phi_all[i]
        out[:, lo + f] += expected[i]
    return out
