"""SHAP feature contributions (reference Tree::PredictContrib, tree.h:139,
recursive TreeSHAP in tree.cpp).  Full implementation lands with the M5
feature set; until then fail loudly rather than silently."""

from __future__ import annotations

import numpy as np


def predict_contrib(trees, X: np.ndarray, num_class: int) -> np.ndarray:
    raise NotImplementedError(
        "predict(pred_contrib=True) (SHAP values) is not implemented yet "
        "in lightgbm_tpu; planned for the constraints/extras milestone")
