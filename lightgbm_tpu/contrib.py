"""SHAP feature contributions, device-side.

TPU-native equivalent of the reference's recursive TreeSHAP
(Tree::PredictContrib, include/LightGBM/tree.h:139; TreeSHAP recursion in
src/io/tree.cpp).  The recursion does not vectorize, so this uses the
per-leaf decomposition (the same reformulation GPUTreeShap uses): for a leaf
l with unique path features U, per row x,

    phi_i += v_l * (o_i - z_i) * sum_k c_k(i) * k! (u-1-k)! / u!

where o_j = 1 iff x satisfies ALL of feature j's splits on the path,
z_j = product of child-cover fractions of feature j's splits, and c_k(i) are
the coefficients of prod_{j in U\\{i}} (z_j + o_j t).  Host code precomputes
the per-leaf path tables once per model; the device evaluates all
(row, leaf, feature) terms with fixed-shape scans — O(L * D^2) per row.

Output layout matches the reference: per-class blocks of [F feature columns
+ bias column], bias = expected value, each row's block summing to the raw
prediction.
"""

from __future__ import annotations

import functools
import math
from typing import List, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["predict_contrib"]

_K_ZERO = 1e-35
_EPS = 1e-12


class _TreePaths(NamedTuple):
    """Per-tree path tables (one leaf per row, padded to max depth D)."""
    step_node: np.ndarray     # [L, D] int32 internal node id (-1 pad)
    step_dir: np.ndarray      # [L, D] bool: path goes LEFT at this node
    slot_of_step: np.ndarray  # [L, D] int32: unique-feature slot of step
    slot_feat: np.ndarray     # [L, D] int32 real feature id (-1 pad)
    slot_z: np.ndarray        # [L, D] f64 cover-fraction product (1.0 pad)
    n_slots: np.ndarray       # [L] int32 (u per leaf)
    leaf_value: np.ndarray    # [L]
    expected: float           # E[f] = sum_l v_l * prod(path covers)


def _tree_paths(tree) -> _TreePaths:
    nl = tree.num_leaves
    if nl <= 1:
        return _TreePaths(np.full((1, 1), -1, np.int32),
                          np.zeros((1, 1), bool),
                          np.zeros((1, 1), np.int32),
                          np.full((1, 1), -1, np.int32),
                          np.ones((1, 1)),
                          np.zeros(1, np.int32),
                          np.asarray([tree.leaf_value[0]]),
                          float(tree.leaf_value[0]))
    paths = []  # per leaf: list of (node, went_left, cover_frac)
    weights = tree.internal_weight
    lweights = tree.leaf_weight
    counts = tree.internal_count
    lcounts = tree.leaf_count

    def node_weight(code):
        if code >= 0:
            w = weights[code]
            return w if w > 0 else float(counts[code])
        leaf = ~code
        w = lweights[leaf]
        return w if w > 0 else float(lcounts[leaf])

    def walk(code, path):
        if code < 0:
            paths.append((~code, list(path)))
            return
        w = node_weight(code)
        for child, went_left in ((tree.left_child[code], True),
                                 (tree.right_child[code], False)):
            frac = node_weight(child) / max(w, _EPS)
            path.append((code, went_left, frac))
            walk(child, path)
            path.pop()

    walk(0, [])
    paths.sort(key=lambda p: p[0])
    D = max(1, max(len(p) for _, p in paths))
    L = nl
    step_node = np.full((L, D), -1, np.int32)
    step_dir = np.zeros((L, D), bool)
    slot_of_step = np.zeros((L, D), np.int32)
    slot_feat = np.full((L, D), -1, np.int32)
    slot_z = np.ones((L, D))
    n_slots = np.zeros(L, np.int32)
    leaf_value = np.zeros(L)
    expected = 0.0
    for leaf, path in paths:
        leaf_value[leaf] = tree.leaf_value[leaf]
        cover = 1.0
        slots = {}
        for s, (node, went_left, frac) in enumerate(path):
            cover *= frac
            feat = int(tree.split_feature[node])
            if feat not in slots:
                slots[feat] = len(slots)
            j = slots[feat]
            step_node[leaf, s] = node
            step_dir[leaf, s] = went_left
            slot_of_step[leaf, s] = j
            slot_feat[leaf, j] = feat
            slot_z[leaf, j] *= frac
        n_slots[leaf] = len(slots)
        expected += tree.leaf_value[leaf] * cover
    return _TreePaths(step_node, step_dir, slot_of_step, slot_feat, slot_z,
                      n_slots, leaf_value, float(expected))


def _go_left_matrix(tree, X: np.ndarray) -> np.ndarray:
    """[N, M] bool: would row go left at each internal node (same decision
    semantics as ops/predict._traverse_one_tree)."""
    ni = max(tree.num_leaves - 1, 1)
    n = X.shape[0]
    out = np.zeros((n, ni), bool)
    for node in range(tree.num_leaves - 1):
        fval = X[:, tree.split_feature[node]]
        d = int(tree.decision_type[node])
        missing_type = (d >> 2) & 3
        default_left = (d & 2) != 0
        isnan = np.isnan(fval)
        if d & 1:  # categorical
            ival = np.where(isnan, -1, fval).astype(np.int64)
            cat_idx = int(tree.threshold[node])
            lo = tree.cat_boundaries[cat_idx]
            hi = tree.cat_boundaries[cat_idx + 1]
            words = np.asarray(tree.cat_threshold[lo:hi], np.uint32)
            word = ival >> 5
            ok = (ival >= 0) & (word < (hi - lo))
            wv = words[np.clip(word, 0, hi - lo - 1)]
            out[:, node] = ok & (((wv >> (ival & 31)) & 1) == 1)
        else:
            fv = np.where(isnan & (missing_type != 2), 0.0, fval)
            iszero = np.abs(fv) < _K_ZERO
            is_missing = ((missing_type == 2) & isnan) | \
                         ((missing_type == 1) & iszero)
            out[:, node] = np.where(is_missing, default_left,
                                    fv <= tree.threshold[node])
    return out


@functools.partial(jax.jit, static_argnames=("num_features",))
def _tree_contrib(go_left, step_node, step_dir, slot_of_step, slot_feat,
                  slot_z, n_slots, leaf_value, fact_w, num_features: int):
    """phi [N, F+1] for one tree given the row decisions at each node."""
    L, D = step_node.shape
    n = go_left.shape[0]

    def per_leaf(leaf_i):
        nodes = step_node[leaf_i]            # [D]
        valid = nodes >= 0
        gl = go_left[:, jnp.clip(nodes, 0, go_left.shape[1] - 1)]  # [N, D]
        passes = jnp.where(valid[None, :],
                           gl == step_dir[leaf_i][None, :], True)
        # o per slot: AND over this slot's steps
        slot_mask = (slot_of_step[leaf_i][None, :] ==
                     jnp.arange(D)[:, None]) & valid[None, :]      # [D, D]
        o = jnp.all(jnp.where(slot_mask[None, :, :], passes[:, None, :],
                              True), axis=2)                       # [N, D]
        u = n_slots[leaf_i]
        slot_valid = jnp.arange(D) < u
        of = jnp.where(slot_valid[None, :], o.astype(jnp.float32), 0.0)
        zf = jnp.where(slot_valid, slot_z[leaf_i].astype(jnp.float32), 1.0)

        # poly = prod_j (z_j + o_j t): coefficients [N, D+1]; padded slots
        # contribute the neutral factor (z=1, o=0)
        def mul(poly, jo_jz):
            jo, jz = jo_jz
            shifted = jnp.concatenate(
                [jnp.zeros((n, 1), poly.dtype), poly[:, :-1]], axis=1)
            return poly * jz + shifted * jo[:, None], None

        init = jnp.zeros((n, D + 1), jnp.float32).at[:, 0].set(1.0)
        poly, _ = jax.lax.scan(mul, init, (of.T, zf))

        w_u = fact_w[u]                                            # [D+1]

        def unwind(i):
            oi = of[:, i]
            zi = zf[i]
            # divide poly by (z_i + o_i t):
            #   o_i=1: synthetic division top-down  c_{k-1} = p_k - c_k z_i
            #   o_i=0: plain scale                  c_k = p_k / z_i
            def div_step(c_prev, k):
                c = poly[:, k] - c_prev * zi
                return c, c

            ks = jnp.arange(D, 0, -1)
            _, cs_o1 = jax.lax.scan(div_step, jnp.zeros((n,)), ks)
            cs_o1 = jnp.moveaxis(cs_o1, 0, 1)[:, ::-1]             # [N, D]
            cs_o0 = poly[:, :D] / jnp.maximum(zi, _EPS)
            cs = jnp.where(oi[:, None] > 0, cs_o1, cs_o0)
            s = (cs * w_u[None, :D]).sum(axis=1)
            return (oi - zi) * s                                   # [N]

        contrib = jax.vmap(unwind)(jnp.arange(D))                  # [D, N]
        contrib = contrib.T * leaf_value[leaf_i]
        contrib = jnp.where(slot_valid[None, :], contrib, 0.0)
        return contrib, slot_feat[leaf_i]

    def body(acc, leaf_i):
        contrib, feats = per_leaf(leaf_i)
        idx = jnp.clip(feats, 0, num_features - 1)
        upd = jnp.where((feats >= 0)[None, :], contrib, 0.0)
        acc = acc.at[:, idx].add(upd)
        return acc, None

    phi = jnp.zeros((n, num_features + 1), jnp.float32)
    phi, _ = jax.lax.scan(body, phi, jnp.arange(L))
    return phi


def _fact_weights(D: int) -> np.ndarray:
    """[u, k] -> k! (u-1-k)! / u! lookup (0 where k >= u)."""
    w = np.zeros((D + 1, D + 1))
    for u in range(1, D + 1):
        for k in range(u):
            w[u, k] = (math.factorial(k) * math.factorial(u - 1 - k)
                       / math.factorial(u))
    return w


def predict_contrib(trees: List, X: np.ndarray, num_class: int) -> np.ndarray:
    """[N, (F+1) * num_class] SHAP values (reference PredictContrib layout:
    per-class blocks of F feature columns + bias column)."""
    X = np.ascontiguousarray(np.asarray(X, np.float64))
    n, f = X.shape
    out = np.zeros((n, (f + 1) * num_class))
    if not trees:
        return out
    paths = [_tree_paths(t) for t in trees]
    # pad every tree to common (L, D) so _tree_contrib compiles ONCE for the
    # whole model (padded leaves: value 0, neutral slots -> zero phi)
    Dmax = max(max(p.step_node.shape[1] for p in paths), 1)
    Lmax = max(max(p.step_node.shape[0] for p in paths), 1)
    fact_w = jnp.asarray(_fact_weights(Dmax), jnp.float32)
    for i, (tree, p) in enumerate(zip(trees, paths)):
        cls = i % num_class
        lo = cls * (f + 1)
        if tree.num_leaves <= 1:
            out[:, lo + f] += tree.leaf_value[0]
            continue
        L, D = p.step_node.shape
        pad = ((0, Lmax - L), (0, Dmax - D))
        gl_np = _go_left_matrix(tree, X)
        gl = jnp.asarray(np.pad(
            gl_np, ((0, 0), (0, max(Lmax - 1, 1) - gl_np.shape[1]))))
        phi = _tree_contrib(
            gl,
            jnp.asarray(np.pad(p.step_node, pad, constant_values=-1)),
            jnp.asarray(np.pad(p.step_dir, pad)),
            jnp.asarray(np.pad(p.slot_of_step, pad)),
            jnp.asarray(np.pad(p.slot_feat, pad, constant_values=-1)),
            jnp.asarray(np.pad(p.slot_z, pad, constant_values=1.0),
                        jnp.float32),
            jnp.asarray(np.pad(p.n_slots, (0, Lmax - L))),
            jnp.asarray(np.pad(p.leaf_value, (0, Lmax - L)), jnp.float32),
            fact_w, num_features=f)
        out[:, lo:lo + f + 1] += np.asarray(phi, np.float64)
        out[:, lo + f] += p.expected
    return out
