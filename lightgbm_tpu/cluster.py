"""Multi-process training launcher: the reference's Dask-orchestration
equivalent, with supervised checkpoint-restart recovery.

Reference python-package/lightgbm/dask.py:67-181,724: the Dask layer's whole
job is cluster plumbing — find open ports, build the `machines` list, launch
one training process per worker with the network params injected, return
worker 0's model.  Here the same orchestration launches local worker
processes joined via jax.distributed (parallel/mesh.py); on a TPU pod each
host runs one worker and the mesh spans all chips over ICI/DCN.

Synchronous-SPMD fault model as in the reference: every worker must
participate in every iteration; a dead worker fails the job (no elasticity),
recovery is checkpoint-restart (SURVEY §5 failure model).  The supervisor in
``train_distributed`` implements that recovery: workers checkpoint through
lightgbm_tpu/checkpoint/ (rank-0-only atomic writes), and when ANY worker
exits abnormally the survivors are killed and the whole job is relaunched —
resuming from the latest checkpoint — with bounded exponential backoff, up
to ``max_restarts`` times.  ``LGBM_TPU_FAULT_ITER`` (checkpoint/fault.py)
makes the path testable by killing a chosen rank at a chosen iteration;
fault env vars are stripped on restart attempts, modelling a transient
preemption.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, Optional, Sequence

from .log import log_info, log_warning

__all__ = ["train_distributed", "continuous_distributed",
           "find_open_ports"]


def find_open_ports(n: int, host: str = "127.0.0.1") -> list:
    """n distinct free ports (reference _find_n_open_ports, dask.py:67)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


_WORKER_TEMPLATE = r"""
import os, sys
sys.path.insert(0, {repo!r})
platform = os.environ.get("LIGHTGBM_TPU_PLATFORM")
if platform:
    import jax
    jax.config.update("jax_platforms", platform)
import numpy as np
import lightgbm_tpu as lgb

try:
    import cloudpickle as _pickler
except ImportError:
    import pickle as _pickler
with open({payload!r}, "rb") as fh:
    job = _pickler.load(fh)
rank = int(os.environ["LIGHTGBM_TPU_RANK"])
X, y, extra = job["data_fn"](rank, job["num_workers"])
params = dict(job["params"])
params.update(job["net_params"])
params["local_listen_port"] = job["ports"][rank]
ds = lgb.Dataset(X, y, **(extra or {{}}))
bst = lgb.train(params, ds, num_boost_round=job["num_boost_round"])
if rank == 0:
    bst.save_model(job["model_out"])
print("LGBM_TPU_WORKER_DONE", rank, flush=True)
"""


def _tail(path: str, n: int = 4000) -> str:
    try:
        with open(path, errors="replace") as fh:
            return fh.read()[-n:]
    except OSError:
        return "<no worker log>"


def _kill_all(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        p.wait()


def _lease_evidence(lease_monitor) -> str:
    if lease_monitor is None:
        return ""
    try:
        rows = lease_monitor.summary()
    except Exception as exc:        # evidence, not a dependency
        return f"\n(lease table unreadable: {exc!r})"
    return "\nlease ages at failure:\n" + "\n".join(
        f"  rank {r['rank']}: {r['state']}"
        + (f" (age {r['age_s']}s, phase={r['phase']}, "
           f"cycle={r['cycle']}, iter={r['iteration']})"
           if r["age_s"] is not None else " (no lease written)")
        for r in rows)


def _supervise(launch, max_restarts: int, backoff_s: float,
               timeout: int, script: str,
               lease_monitor=None, launch_one=None) -> None:
    """Synchronous-SPMD supervision shared by the training launcher and
    the sharded continuous fleet: poll worker processes; on any abnormal
    exit (or a hung attempt past ``timeout``) kill the survivors and
    relaunch the WHOLE job — workers recover from their own persistent
    state (checkpoints / ingest journals) — with bounded exponential
    backoff up to ``max_restarts``.  ``launch(attempt) -> (procs,
    logs)``; fault env stripping per attempt is the launcher's job.

    **Gray-failure supervision** (``lease_monitor`` + ``launch_one``,
    the continuous fleet): a worker whose process is ALIVE but whose
    rank lease has gone stalled is a gray failure no exit code will ever
    report.  The supervisor kills and relaunches ONLY that worker
    (``launch_one(rank, attempt, solo) -> (proc, log)``); the relaunched
    rank recovers from its journal and asks the surviving quorum for
    re-admission.  Solo relaunches share the ``max_restarts`` budget,
    and every budget-exhausted error carries the lease-age table — the
    evidence of who was stalled, slow, or fresh when the budget died."""
    attempt = 0
    solo_restarts = 0
    while True:
        procs, logs = launch(attempt)
        # grace window per rank: a just-(re)launched worker's lease
        # still carries its pre-kill age until recovery writes the
        # first heartbeat — judging it stalled in that window would
        # kill-loop the relaunch
        grace = getattr(lease_monitor, "stalled_after_s", 60.0)
        launched_at = [time.time()] * len(procs)
        deadline = time.time() + timeout
        failed_rank = None
        hung = False
        while True:
            if lease_monitor is not None and launch_one is not None:
                for r in lease_monitor.stalled_ranks():
                    if procs[r].poll() is not None:
                        continue     # dead, not gray: the rc path below
                    if time.time() - launched_at[r] < grace:
                        continue     # lease may predate the relaunch
                    if solo_restarts + attempt >= max_restarts:
                        _kill_all(procs)
                        raise RuntimeError(
                            f"worker {r} is stalled (alive, lease "
                            "expired) and the restart budget is "
                            f"exhausted ({solo_restarts} solo + "
                            f"{attempt} fleet restarts of "
                            f"{max_restarts});"
                            f"{_lease_evidence(lease_monitor)}\n"
                            f"--- tail of rank {r} ---\n"
                            f"{_tail(logs[r])}")
                    log_warning(
                        f"worker {r} is STALLED (process alive, lease "
                        "expired): killing and relaunching only it "
                        f"(solo restart {solo_restarts + 1});"
                        f"{_lease_evidence(lease_monitor)}")
                    procs[r].kill()
                    procs[r].wait()
                    procs[r], logs[r] = launch_one(r, attempt,
                                                   solo_restarts)
                    launched_at[r] = time.time()
                    solo_restarts += 1
            rcs = [p.poll() for p in procs]
            bad = [r for r, rc in enumerate(rcs) if rc not in (None, 0)]
            if bad:
                failed_rank = bad[0]
                break
            if all(rc == 0 for rc in rcs):
                break
            if time.time() > deadline:
                # a preempted worker often HANGS (survivors block in
                # collectives) rather than exiting: a timed-out attempt
                # is a failure like any other and consumes a restart
                hung = True
                failed_rank = next((r for r, rc in enumerate(rcs)
                                    if rc is None), 0)
                break
            time.sleep(0.2)
        if failed_rank is None:
            return                   # every worker exited cleanly
        # synchronous SPMD: one death stalls everyone — kill the
        # survivors, then decide whether the restart budget allows a
        # relaunch from persistent state
        rc = procs[failed_rank].returncode
        _kill_all(procs)
        why = (f"hung past the {timeout}s attempt deadline" if hung
               else f"died (rc={rc})")
        if attempt + solo_restarts >= max_restarts:
            if hung:
                raise subprocess.TimeoutExpired(
                    cmd=f"{sys.executable} {script}", timeout=timeout)
            log_list = "\n".join(f"  rank {r}: {p}"
                                 for r, p in enumerate(logs))
            raise RuntimeError(
                f"worker {failed_rank} failed (rc={rc}) and the restart "
                f"budget is exhausted ({attempt}/{max_restarts} restarts "
                f"used);{_lease_evidence(lease_monitor)}\n"
                f"worker logs:\n{log_list}\n"
                f"--- tail of rank {failed_rank} ---\n"
                f"{_tail(logs[failed_rank])}")
        delay = backoff_s * (2.0 ** attempt)
        log_warning(
            f"worker {failed_rank} {why}; killed survivors, "
            f"relaunching from persistent state in {delay:.1f}s "
            f"(restart {attempt + 1}/{max_restarts})")
        if delay > 0:
            time.sleep(delay)
        attempt += 1


def train_distributed(params: Dict, data_fn: Callable, num_boost_round: int,
                      num_workers: int = 2,
                      hosts: Optional[Sequence[str]] = None,
                      platform: Optional[str] = None,
                      timeout: int = 3600):
    """Train across ``num_workers`` processes and return the final Booster.

    data_fn(rank, num_workers) -> (X, y, extra_dataset_kwargs|None) runs in
    each worker and must be picklable (reference _train_part receives its
    dask partition the same way, dask.py:164).  Workers join through
    jax.distributed using an auto-built `machines` list; training runs
    whatever ``tree_learner`` the params select (default data-parallel).

    Data partitioning (reference _split_to_parts, dask.py:341): pass
    ``pre_partition=True`` in params and have data_fn return only THIS
    rank's rows — each worker then bins just its shard and the learner
    consumes rank-local blocks (TrainDataset.from_rank_shard), so per-rank
    memory is O(N/num_workers).  Without it, every worker must return the
    FULL dataset (reference pre_partition=false semantics).

    Fault tolerance: when ``max_restarts`` (param, default 2) is positive,
    workers checkpoint into ``checkpoint_dir`` (param; defaults to a
    job-private temp directory) and the supervisor relaunches the whole
    job from the latest checkpoint after any worker death, waiting
    ``restart_backoff_s * 2**attempt`` between attempts.  Each attempt
    gets fresh ports (the dead mesh's ports may sit in TIME_WAIT).
    ``timeout`` bounds each attempt, not the total.

    Only localhost launch is implemented — on a multi-host pod, start one
    process per host yourself with LIGHTGBM_TPU_RANK + the same params and
    this module's machines list convention; ``checkpoint_dir`` must then
    live on storage shared by every host.
    """
    if hosts is None:
        hosts = ["127.0.0.1"] * num_workers
    params = dict(params)
    max_restarts = int(params.get("max_restarts", 2) or 0)
    backoff_s = float(params.get("restart_backoff_s", 1.0) or 0.0)

    tmp = tempfile.mkdtemp(prefix="lgbm_tpu_cluster_")
    model_out = os.path.join(tmp, "model.txt")
    from .config import coerce_bool
    if coerce_bool(params.get("telemetry", False)) \
            and not params.get("telemetry_dir"):
        # per-rank JSONL event logs land next to the worker logs; the
        # supervisor rolls them up into telemetry_summary.json on exit
        params["telemetry_dir"] = os.path.join(tmp, "telemetry")
    if max_restarts > 0 and not params.get("aot_bundle_dir"):
        # relaunched workers recompile everything a fresh process needs;
        # a job-shared AOT bundle (lightgbm_tpu/aot/) lets the restart
        # deserialize the fused training programs the first attempt
        # compiled instead — the bundle lives next to the checkpoints,
        # so on a multi-host pod both ride the same shared storage
        params["aot_bundle_dir"] = os.path.join(tmp, "aot_bundle")
    if max_restarts > 0 and not params.get("checkpoint_dir"):
        # restarts without checkpoints would replay the whole run; give
        # the job a private checkpoint directory so resume is automatic.
        # Auto-provisioned checkpointing defaults to ~10 saves per run,
        # not every iteration (full-state saves re-serialize the whole
        # tree list and sync the device pipeline) — an explicit
        # checkpoint_freq in params still wins.
        params["checkpoint_dir"] = os.path.join(tmp, "checkpoints")
        if int(params.get("checkpoint_freq", -1) or -1) <= 0:
            params["checkpoint_freq"] = max(1, num_boost_round // 10)
    try:
        import cloudpickle as _pickler
    except ImportError:          # data_fn must then be importable by name
        import pickle as _pickler
    script = os.path.join(tmp, "worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _launch(attempt: int):
        """One attempt: fresh ports/payload, one process per rank with
        stdout+stderr to per-attempt log files (no PIPE: a supervisor that
        polls instead of reading must not let a chatty worker block)."""
        ports = find_open_ports(num_workers)
        machines = ",".join(f"{h}:{p}" for h, p in zip(hosts, ports))
        log_info(f"launching {num_workers} workers (attempt {attempt}): "
                 f"{machines}")
        net_params = {"num_machines": num_workers, "machines": machines,
                      "tree_learner": params.get("tree_learner", "data"),
                      "num_tpu_devices": params.get("num_tpu_devices", 0)}
        payload = os.path.join(tmp, f"job_a{attempt}.pkl")
        with open(payload, "wb") as fh:
            _pickler.dump({"params": params, "net_params": net_params,
                           "data_fn": data_fn, "ports": ports,
                           "num_workers": num_workers,
                           "num_boost_round": num_boost_round,
                           "model_out": model_out}, fh)
        with open(script, "w") as fh:
            fh.write(_WORKER_TEMPLATE.format(repo=repo, payload=payload))
        procs, logs = [], []
        for rank in range(num_workers):
            env = dict(os.environ)
            env["LIGHTGBM_TPU_RANK"] = str(rank)
            if platform:
                env["LIGHTGBM_TPU_PLATFORM"] = platform
                env["JAX_PLATFORMS"] = platform
            if attempt > 0:
                # transient-fault model: an injected fault does not recur
                # on the relaunch (checkpoint/fault.py)
                from .checkpoint.fault import FAULT_ENV_VARS
                for var in FAULT_ENV_VARS:
                    env.pop(var, None)
            log_path = os.path.join(tmp, f"worker_{rank}_a{attempt}.log")
            logs.append(log_path)
            # rank-prefixed at spawn so failed-run triage never requires
            # knowing the tmp layout
            log_info(f"worker {rank} log: {log_path}")
            log_fh = open(log_path, "w")
            procs.append(subprocess.Popen(
                [sys.executable, script], env=env,
                stdout=log_fh, stderr=subprocess.STDOUT, text=True))
            log_fh.close()       # the child keeps its own handle
        return procs, logs

    _supervise(_launch, max_restarts, backoff_s, timeout, script)

    tdir = params.get("telemetry_dir")
    if tdir and os.path.isdir(tdir):
        # job-level rollup of every rank's JSONL (records accumulate per
        # rank across supervised restarts, so the summary covers them too)
        try:
            from .telemetry.export import rollup_telemetry_dir
            summary = rollup_telemetry_dir(tdir)
            if summary is not None:
                log_info(
                    f"telemetry rollup ({summary['ranks']} ranks, "
                    f"{summary['total_iterations']} iterations): "
                    f"{summary['path']}")
        except Exception as exc:   # a rollup bug must not fail the job
            log_warning(f"telemetry rollup failed: {exc!r}")

    from .basic import Booster
    return Booster(model_file=model_out)


def continuous_distributed(params: Dict, num_workers: int = 2,
                           hosts: Optional[Sequence[str]] = None,
                           platform: Optional[str] = None,
                           timeout: int = 3600,
                           log_dir: Optional[str] = None):
    """Launch + supervise a SHARDED continuous fleet on localhost: one
    ``task=continuous`` CLI worker per rank (``continuous_shards`` set
    for them), joined through jax.distributed, each tailing its shard of
    ``continuous_source`` into ``continuous_dir`` (REQUIRED — it holds
    the fleet's shared mapper artifacts, ingest journals, and commit
    record, so it must be storage every worker sees).

    Supervision is the same synchronous-SPMD contract as
    ``train_distributed``: any worker death (``LGBM_TPU_FAULT_CYCLE``
    makes one schedulable) kills the survivors and relaunches the whole
    fleet with fresh ports and fault env stripped; relaunched workers
    recover from their ingest journals + the commit record and replay
    the in-flight cycle to a bit-identical model.

    Workers exit cleanly via ``continuous_max_cycles`` /
    ``continuous_max_idle_polls``.  Returns the committed model as a
    Booster (None when no cycle ever committed a model)."""
    if hosts is None:
        hosts = ["127.0.0.1"] * num_workers
    params = dict(params)
    workdir = params.get("continuous_dir")
    if not workdir:
        raise ValueError("continuous_distributed requires continuous_dir="
                         "shared storage (fleet journals + commit record)")
    if not params.get("continuous_source"):
        raise ValueError("continuous_distributed requires "
                         "continuous_source=DIR")
    max_restarts = int(params.get("max_restarts", 2) or 0)
    backoff_s = float(params.get("restart_backoff_s", 1.0) or 0.0)
    params["task"] = "continuous"
    params["continuous_shards"] = num_workers
    params.pop("max_restarts", None)
    params.pop("restart_backoff_s", None)
    tmp = log_dir or tempfile.mkdtemp(prefix="lgbm_tpu_fleet_cont_")
    os.makedirs(tmp, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _spawn_worker(rank: int, machines: str, ports, attempt: int,
                      strip_faults: bool, log_path: str):
        argv = dict(params)
        argv["num_machines"] = num_workers
        argv["machines"] = machines
        argv["local_listen_port"] = ports[rank]
        # every rank serves its own registry copy: one port each
        # (0 = train/gate only, the localhost-fleet default — a
        # front door would sit behind fleet/router.py anyway)
        base_port = int(params.get("serving_port", 0) or 0)
        argv["serving_port"] = (base_port + rank) if base_port else 0
        cmd = [sys.executable, "-m", "lightgbm_tpu"] + [
            f"{k}={v}" for k, v in argv.items()]
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        # attempt-namespaced coordination files (FleetComm): a killed
        # attempt's stale barrier tokens / exchange payloads can never
        # satisfy a fresh attempt's collectives
        env["LIGHTGBM_TPU_FLEET_ATTEMPT"] = str(attempt)
        env["PYTHONPATH"] = repo + os.pathsep + env.get(
            "PYTHONPATH", "")
        if platform:
            env["LIGHTGBM_TPU_PLATFORM"] = platform
            env["JAX_PLATFORMS"] = platform
        if strip_faults:
            # transient-fault model: an injected fault does not
            # recur on the relaunch (checkpoint/fault.py)
            from .checkpoint.fault import FAULT_ENV_VARS
            for var in FAULT_ENV_VARS:
                env.pop(var, None)
        log_info(f"continuous worker {rank} log: {log_path}")
        log_fh = open(log_path, "w")
        proc = subprocess.Popen(cmd, env=env, stdout=log_fh,
                                stderr=subprocess.STDOUT, text=True)
        log_fh.close()       # the child keeps its own handle
        return proc

    launch_state = {"machines": "", "ports": []}

    def _launch(attempt: int):
        ports = find_open_ports(num_workers)
        machines = ",".join(f"{h}:{p}" for h, p in zip(hosts, ports))
        launch_state["machines"] = machines
        launch_state["ports"] = ports
        log_info(f"launching {num_workers} continuous workers "
                 f"(attempt {attempt}): {machines}")
        procs, logs = [], []
        for rank in range(num_workers):
            log_path = os.path.join(tmp, f"worker_{rank}_a{attempt}.log")
            logs.append(log_path)
            procs.append(_spawn_worker(rank, machines, ports, attempt,
                                       strip_faults=attempt > 0,
                                       log_path=log_path))
        return procs, logs

    def _launch_one(rank: int, attempt: int, solo: int):
        """Gray-failure targeted relaunch: only the stalled worker comes
        back (same fleet attempt — it must share the survivors'
        coordination namespace to be re-admitted), faults stripped."""
        log_path = os.path.join(
            tmp, f"worker_{rank}_a{attempt}s{solo}.log")
        proc = _spawn_worker(rank, launch_state["machines"],
                             launch_state["ports"], attempt,
                             strip_faults=True, log_path=log_path)
        return proc, log_path

    # lease-age supervision: only meaningful when the quorum machinery
    # is on (rank timeout > 0).  The stalled threshold sits well past
    # the in-process vote window so quorum exclusion gets first shot and
    # the supervisor's kill is the recovery of last resort.
    rank_timeout = float(params.get("fleet_train_rank_timeout_s",
                                    60.0) or 0.0)
    lease_monitor = None
    if rank_timeout > 0:
        from .continuous.lease import LeaseMonitor
        lease_monitor = LeaseMonitor(
            f"{workdir.rstrip('/')}/fleet", num_workers,
            slow_after_s=rank_timeout,
            stalled_after_s=3.0 * rank_timeout)

    _supervise(_launch, max_restarts, backoff_s, timeout,
               "python -m lightgbm_tpu task=continuous",
               lease_monitor=lease_monitor, launch_one=_launch_one)
    # the fleet's single source of truth for "what is committed"
    import json as _json

    from .io import file_io
    try:
        state = _json.loads(file_io.read_text(
            f"{workdir}/fleet/commit_state.json"))
    except OSError:
        return None
    if not state.get("model_file"):
        return None
    from .basic import Booster
    return Booster(model_str=file_io.read_text(state["model_file"]))
