"""Multi-process training launcher: the reference's Dask-orchestration
equivalent.

Reference python-package/lightgbm/dask.py:67-181,724: the Dask layer's whole
job is cluster plumbing — find open ports, build the `machines` list, launch
one training process per worker with the network params injected, return
worker 0's model.  Here the same orchestration launches local worker
processes joined via jax.distributed (parallel/mesh.py); on a TPU pod each
host runs one worker and the mesh spans all chips over ICI/DCN.

Synchronous-SPMD fault model as in the reference: every worker must
participate in every iteration; a dead worker fails the job (no elasticity),
recovery is checkpoint-restart (SURVEY §5 failure model).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
from typing import Callable, Dict, Optional, Sequence

from .log import log_info

__all__ = ["train_distributed", "find_open_ports"]


def find_open_ports(n: int, host: str = "127.0.0.1") -> list:
    """n distinct free ports (reference _find_n_open_ports, dask.py:67)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


_WORKER_TEMPLATE = r"""
import os, sys
sys.path.insert(0, {repo!r})
platform = os.environ.get("LIGHTGBM_TPU_PLATFORM")
if platform:
    import jax
    jax.config.update("jax_platforms", platform)
import numpy as np
import lightgbm_tpu as lgb

try:
    import cloudpickle as _pickler
except ImportError:
    import pickle as _pickler
with open({payload!r}, "rb") as fh:
    job = _pickler.load(fh)
rank = int(os.environ["LIGHTGBM_TPU_RANK"])
X, y, extra = job["data_fn"](rank, job["num_workers"])
params = dict(job["params"])
params.update(job["net_params"])
params["local_listen_port"] = job["ports"][rank]
ds = lgb.Dataset(X, y, **(extra or {{}}))
bst = lgb.train(params, ds, num_boost_round=job["num_boost_round"])
if rank == 0:
    bst.save_model(job["model_out"])
print("LGBM_TPU_WORKER_DONE", rank, flush=True)
"""


def train_distributed(params: Dict, data_fn: Callable, num_boost_round: int,
                      num_workers: int = 2,
                      hosts: Optional[Sequence[str]] = None,
                      platform: Optional[str] = None,
                      timeout: int = 3600):
    """Train across ``num_workers`` processes and return the final Booster.

    data_fn(rank, num_workers) -> (X, y, extra_dataset_kwargs|None) runs in
    each worker and must be picklable (reference _train_part receives its
    dask partition the same way, dask.py:164).  Workers join through
    jax.distributed using an auto-built `machines` list; training runs
    whatever ``tree_learner`` the params select (default data-parallel).

    Data partitioning (reference _split_to_parts, dask.py:341): pass
    ``pre_partition=True`` in params and have data_fn return only THIS
    rank's rows — each worker then bins just its shard and the learner
    consumes rank-local blocks (TrainDataset.from_rank_shard), so per-rank
    memory is O(N/num_workers).  Without it, every worker must return the
    FULL dataset (reference pre_partition=false semantics).

    Only localhost launch is implemented — on a multi-host pod, start one
    process per host yourself with LIGHTGBM_TPU_RANK + the same params and
    this module's machines list convention.
    """
    if hosts is None:
        hosts = ["127.0.0.1"] * num_workers
    ports = find_open_ports(num_workers)
    machines = ",".join(f"{h}:{p}" for h, p in zip(hosts, ports))
    log_info(f"launching {num_workers} workers: {machines}")

    tmp = tempfile.mkdtemp(prefix="lgbm_tpu_cluster_")
    payload = os.path.join(tmp, "job.pkl")
    model_out = os.path.join(tmp, "model.txt")
    net_params = {"num_machines": num_workers, "machines": machines,
                  "tree_learner": params.get("tree_learner", "data"),
                  "num_tpu_devices": params.get("num_tpu_devices", 0)}
    try:
        import cloudpickle as _pickler
    except ImportError:          # data_fn must then be importable by name
        import pickle as _pickler
    with open(payload, "wb") as fh:
        _pickler.dump({"params": params, "net_params": net_params,
                     "data_fn": data_fn, "ports": ports,
                     "num_workers": num_workers,
                     "num_boost_round": num_boost_round,
                     "model_out": model_out}, fh)
    script = os.path.join(tmp, "worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(script, "w") as fh:
        fh.write(_WORKER_TEMPLATE.format(repo=repo, payload=payload))

    procs = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env["LIGHTGBM_TPU_RANK"] = str(rank)
        if platform:
            env["LIGHTGBM_TPU_PLATFORM"] = platform
            env["JAX_PLATFORMS"] = platform
        procs.append(subprocess.Popen(
            [sys.executable, script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(stdout)
    for rank, (p, text) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"worker {rank} failed (rc={p.returncode}):\n{text[-4000:]}")
    from .basic import Booster
    return Booster(model_file=model_out)
