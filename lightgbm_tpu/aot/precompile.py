"""Precompilation entry points: populate a program bundle ahead of time.

The CLI (``task=precompile``, application.py) and bench drive these; both
halves are also callable directly:

- ``precompile_training(params, train_set, ...)`` AOT-compiles the fused
  multi-round training blocks for the dataset's exact shapes — every
  (variant, K) pair a run visits — and persists them to the bundle.  A
  later ``train()`` with the same ``aot_bundle_dir`` (same machine class,
  same shapes/config) then loads instead of compiling, which is what makes
  cold trainer starts, supervised restarts (cluster.py), and repeated CI
  runs cheap.

- ``precompile_predictor(model, ...)`` warms a serving
  ``CompiledPredictor``'s bucket ladder and serializes the resulting
  executables, so a replica can ``load_bundle`` at publish time and serve
  its first request with zero compiles (serving/compiled.py).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..log import log_info

__all__ = ["precompile_training", "precompile_predictor",
           "default_bundle_dir"]


def default_bundle_dir(model_path: str) -> str:
    """The convention for a bundle living next to its model."""
    return str(model_path) + ".aot"


def precompile_training(params: Dict, train_set, bundle_dir: str,
                        rounds: Optional[int] = None) -> Dict:
    """AOT-compile the fused training programs for ``train_set``'s shapes
    into ``bundle_dir`` without training.  Returns a summary dict."""
    from ..basic import Booster
    params = dict(params)
    params["aot_bundle_dir"] = str(bundle_dir)
    t0 = time.perf_counter()
    booster = Booster(params=params, train_set=train_set)
    out = booster._gbdt.precompile_fused(rounds)
    out["seconds"] = round(time.perf_counter() - t0, 3)
    out["bundle_dir"] = str(bundle_dir)
    if not out.get("supported"):
        log_info("aot precompile: this config has no fused training "
                 "program (parallel learner, custom objective, valid sets "
                 "or telemetry=on) — nothing to bundle for training")
    else:
        log_info(f"aot precompile: {out['programs']} training program(s) "
                 f"ready in {out['seconds']}s ({bundle_dir})")
    return out


def precompile_predictor(model, bundle_dir: str, buckets=None, dtype=None,
                         kinds=("prob", "raw")) -> Dict:
    """Warm a CompiledPredictor for ``model`` (a Booster or a model file
    path) across its bucket ladder and serialize every program into
    ``bundle_dir``.  Returns a summary dict."""
    from ..basic import Booster
    if isinstance(model, str):
        model = Booster(model_file=model)
    t0 = time.perf_counter()
    pred = model.to_compiled(buckets=buckets, dtype=dtype)
    compiled = pred.warmup(kinds=kinds)
    saved = pred.save_bundle(bundle_dir)
    dt = round(time.perf_counter() - t0, 3)
    log_info(f"aot precompile: {saved} predict program(s) "
             f"({compiled} freshly compiled) ready in {dt}s ({bundle_dir})")
    return {"programs": saved, "compiled": compiled, "seconds": dt,
            "bundle_dir": str(bundle_dir)}
