"""Ahead-of-time compilation subsystem.

Two halves (see bundle.py and precompile.py):

- **Program bundles** (``ProgramBundle``): versioned on-disk artifacts —
  a manifest plus serialized XLA executables keyed by a structured
  signature (shapes, dtypes, config fingerprint, jax/backend/topology).
  Consumers go through ``resolve_program``: load on a signature match,
  recompile (with the differing keys logged) on any mismatch, and save
  the fresh executable back so the next cold process loads instead.

- **Precompilation** (``precompile_training`` / ``precompile_predictor``,
  CLI ``task=precompile``): build every program a run will need — the
  fused multi-round training blocks for a dataset's exact shapes, the
  serving predictor's bucket ladder — ahead of time, so trainers,
  checkpoint-restarted workers, and serving replicas all start warm with
  zero steady-state XLA compiles.
"""

from .bundle import (BUNDLE_VERSION, ProgramBundle, describe_mismatch,
                     resolve_program, runtime_signature,
                     signature_fingerprint)
from .precompile import (default_bundle_dir, precompile_predictor,
                         precompile_training)

__all__ = ["BUNDLE_VERSION", "ProgramBundle", "describe_mismatch",
           "resolve_program", "runtime_signature", "signature_fingerprint",
           "default_bundle_dir", "precompile_predictor",
           "precompile_training"]
