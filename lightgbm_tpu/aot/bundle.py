"""Versioned AOT program bundles: serialized XLA executables as artifacts.

The reference ships AOT-compiled kernels inside its binary, so a cold
process pays zero compilation; the JAX stack instead JIT-compiles the
grower/predict programs on first use — BENCH_r05 measured 17.3 s of that
against 7.2 s of actual boosting.  A ``ProgramBundle`` closes the gap by
making compilation a *build artifact*: executables are AOT-lowered once
(``jax.jit(...).lower(...).compile()``), serialized with
``jax.experimental.serialize_executable``, and persisted next to the model
as a manifest + one program file per entry.  A later process (trainer,
restarted worker, serving replica) deserializes instead of compiling.

Every entry carries a structured **signature** — shapes, dtypes, config
fingerprint, jax version, backend, device count — and loading is
load-or-recompile: any mismatch falls back to a fresh compile with the
differing keys logged, never a wrong or crashing program.  All IO goes
through the ``io/file_io`` scheme registry, so bundles live wherever
checkpoints do (local disk, ``file://``, or any registered scheme).

Layout (``bundle_dir/``)::

    MANIFEST.json                  {"bundle_version": 1, "programs": {...}}
    <name>.xprog                   pickled (blob, in_tree, out_tree)
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import time
from typing import Callable, Dict, Optional, Tuple

from ..io import file_io
from ..log import log_info, log_warning

__all__ = ["BUNDLE_VERSION", "ProgramBundle", "runtime_signature",
           "signature_fingerprint", "describe_mismatch", "resolve_program",
           "serializable_compiles"]


@contextlib.contextmanager
def serializable_compiles():
    """Compile with jax's persistent compilation cache OFF.

    An executable that jax itself loaded from its persistent cache
    re-serializes INCOMPLETELY on the CPU backend — the blob drops the
    parallel-codegen split modules and deserialization dies with
    "Symbols not found" (verified on jax 0.4.37).  Anything destined for
    a bundle must therefore come from a genuine codegen run; the bundle
    replaces the persistent cache for these programs anyway."""
    import jax

    def _reset():
        # jax memoizes the is-cache-used decision per process; without a
        # reset the flag flip is silently ignored (same trap
        # compile_cache.py documents for the cache DIR update)
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass

    try:
        prev = bool(jax.config.jax_enable_compilation_cache)
    except AttributeError:        # config name drift: nothing to disable
        yield
        return
    jax.config.update("jax_enable_compilation_cache", False)
    _reset()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        _reset()

BUNDLE_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"


def runtime_signature() -> Dict:
    """The runtime facts a serialized executable is only valid for: an XLA
    executable is compiled for one backend/topology and one jax version —
    loading it anywhere else is undefined, so these keys gate every load."""
    import jax
    return {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": int(jax.device_count()),
        "process_count": int(jax.process_count()),
    }


def _canonical(sig: Dict) -> Dict:
    """JSON round-trip so tuples/np scalars compare equal to their loaded
    (list/int) forms."""
    return json.loads(json.dumps(sig, sort_keys=True, default=str))


def signature_fingerprint(sig: Dict) -> str:
    blob = json.dumps(_canonical(sig), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def describe_mismatch(expected: Dict, found: Dict) -> str:
    """Human-readable reason string naming exactly which signature keys
    differ (the logged 'why we recompiled')."""
    expected, found = _canonical(expected), _canonical(found)
    diffs = []
    for key in sorted(set(expected) | set(found)):
        e, f = expected.get(key, "<absent>"), found.get(key, "<absent>")
        if e != f:
            diffs.append(f"{key}: bundle has {f!r}, run needs {e!r}")
    return "; ".join(diffs) if diffs else "signatures differ"


def _join(base: str, name: str) -> str:
    return base.rstrip("/") + "/" + name


class ProgramBundle:
    """One bundle directory: manifest + serialized executables.

    Single-writer semantics like the checkpoint manager: program files are
    committed tmp+rename, the manifest is rewritten whole (read-modify-
    write) after each save.  Readers only ever see committed files.
    """

    def __init__(self, path: str):
        self.path = str(path)

    # -- manifest -------------------------------------------------------
    def _manifest_path(self) -> str:
        return _join(self.path, MANIFEST_NAME)

    def _raw_manifest(self) -> Optional[Dict]:
        if not file_io.exists(self._manifest_path()):
            return None
        with file_io.open_readable(self._manifest_path()) as fh:
            return json.load(fh)

    def manifest(self) -> Dict:
        man = self._raw_manifest()
        if man is None:
            return {"bundle_version": BUNDLE_VERSION, "programs": {}}
        if int(man.get("bundle_version", -1)) != BUNDLE_VERSION:
            log_warning(
                f"aot bundle at {self.path!r} has version "
                f"{man.get('bundle_version')!r} (this build reads "
                f"{BUNDLE_VERSION}); ignoring its programs")
            return {"bundle_version": BUNDLE_VERSION, "programs": {}}
        man.setdefault("programs", {})
        return man

    def _write_manifest(self, man: Dict) -> None:
        # pid-suffixed tmp: saves are rank-0-gated (resolve_program callers)
        # but an unrelated process racing the same bundle dir must at worst
        # lose a manifest entry, never interleave bytes in one tmp file
        tmp = self._manifest_path() + f".tmp.{os.getpid()}"
        try:
            with file_io.open_writable(tmp) as fh:
                json.dump(man, fh, indent=1, sort_keys=True, default=str)
            file_io.rename(tmp, self._manifest_path())
        except Exception:
            # a torn/failed write must leave no .tmp litter and, because
            # the rename never ran, no manifest change at all
            try:
                file_io.remove(tmp)
            except OSError:
                pass
            raise

    def program_names(self) -> list:
        return sorted(self.manifest()["programs"])

    def entry(self, name: str) -> Optional[Dict]:
        return self.manifest()["programs"].get(name)

    # -- save / load ----------------------------------------------------
    def save_program(self, name: str, signature: Dict, compiled) -> None:
        """Serialize one compiled executable under ``name`` and commit it
        (program file tmp+rename first, manifest second — a crash between
        the two leaves an orphan file, never a dangling manifest entry)."""
        from jax.experimental import serialize_executable as se
        raw = self._raw_manifest()
        if raw is not None and \
                int(raw.get("bundle_version", -1)) != BUNDLE_VERSION:
            # never downgrade-clobber a bundle written by another build's
            # format (manifest() would read it as empty and the rewrite
            # below would erase every entry the other build saved)
            raise OSError(
                f"bundle at {self.path!r} has version "
                f"{raw.get('bundle_version')!r}; this build writes "
                f"{BUNDLE_VERSION} and will not overwrite it")
        blob, in_tree, out_tree = se.serialize(compiled)
        # verify BEFORE committing: a blob that cannot load back (e.g. the
        # executable was itself a persistent-cache hit — see
        # serializable_compiles) must never enter the manifest, where every
        # later cold start would trip over it
        se.deserialize_and_load(blob, in_tree, out_tree)
        file_io.makedirs(self.path)
        fname = f"{name}.xprog"
        payload = pickle.dumps((blob, in_tree, out_tree),
                               protocol=pickle.HIGHEST_PROTOCOL)
        tmp = _join(self.path, fname + f".tmp.{os.getpid()}")
        try:
            with file_io.open_writable(tmp, binary=True) as fh:
                fh.write(payload)
            file_io.rename(tmp, _join(self.path, fname))
        except Exception:
            try:
                file_io.remove(tmp)
            except OSError:
                pass
            raise
        man = self.manifest()
        man["programs"][name] = {
            "file": fname,
            # content hash verified on every load: a flipped bit in a
            # pickled executable blob deserializes into anything from a
            # crash to a silently wrong program — the one failure mode the
            # signature match cannot catch
            "sha256": hashlib.sha256(payload).hexdigest(),
            "signature": _canonical(signature),
            "fingerprint": signature_fingerprint(signature),
            "saved_at": time.time(),
        }
        self._write_manifest(man)

    def load_program(self, name: str, signature: Dict,
                     manifest: Optional[Dict] = None
                     ) -> Tuple[Optional[object], str]:
        """(executable, "") on a signature match, else (None, reason).

        Never raises for a bad/missing/stale bundle — the caller always has
        the recompile fallback, so every failure mode reduces to a reason
        string it can log.  Callers resolving many programs pass one
        ``manifest()`` snapshot instead of re-reading it per program."""
        try:
            if manifest is None:
                manifest = self.manifest()
            entry = manifest["programs"].get(name)
        except Exception as exc:
            return None, f"unreadable manifest at {self.path!r}: {exc!r}"
        if entry is None:
            return None, f"no program {name!r} in bundle {self.path!r}"
        if entry.get("fingerprint") != signature_fingerprint(signature):
            return None, describe_mismatch(signature,
                                           entry.get("signature", {}))
        try:
            from jax.experimental import serialize_executable as se
            payload = file_io.read_bytes(_join(self.path, entry["file"]))
            want = entry.get("sha256")
            if want is not None:
                got = hashlib.sha256(payload).hexdigest()
                if got != want:
                    # never unpickle bytes that failed their hash —
                    # corruption reduces to the recompile fallback, with
                    # the reason logged like any other miss
                    return None, (
                        f"program {name!r} failed its sha256 check "
                        f"(manifest {want[:12]}…, file {got[:12]}…): "
                        "bundle file corrupt")
            blob, in_tree, out_tree = pickle.loads(payload)
            return se.deserialize_and_load(blob, in_tree, out_tree), ""
        except Exception as exc:
            return None, (f"failed to deserialize {name!r} from "
                          f"{self.path!r}: {exc!r}")


def resolve_program(bundle_dir: str, name: str, signature: Dict,
                    build_lowered: Callable[[], object],
                    save_on_miss: bool = True,
                    stats: Optional[Dict] = None):
    """Load ``name`` from the bundle or compile it — the subsystem's single
    load-or-recompile seam.

    ``build_lowered`` is called only on a miss and must return a
    ``jax.stages.Lowered`` (the caller owns tracing, which needs its
    arguments).  On a miss the freshly compiled executable is saved back
    (best-effort) so the *next* cold process loads instead of compiling.
    ``stats`` (optional dict) accumulates ``aot_load_s`` / ``loaded`` /
    ``compiled`` for benchmarks and tests.
    """
    bundle = ProgramBundle(bundle_dir)
    t0 = time.perf_counter()
    compiled, reason = bundle.load_program(name, signature)
    if compiled is not None:
        dt = time.perf_counter() - t0
        log_info(f"aot: loaded program {name!r} from bundle "
                 f"{bundle_dir!r} in {dt:.3f}s")
        if stats is not None:
            stats["aot_load_s"] = stats.get("aot_load_s", 0.0) + dt
            stats["loaded"] = stats.get("loaded", 0) + 1
        return compiled, True
    log_warning(f"aot: compiling {name!r} (bundle miss: {reason})")
    if save_on_miss:
        # cache-off is only needed when the result will be serialize()d
        # (see serializable_compiles); non-writer ranks keep the persistent
        # compile cache's fast path
        with serializable_compiles():
            compiled = build_lowered().compile()
    else:
        compiled = build_lowered().compile()
    if stats is not None:
        stats["compiled"] = stats.get("compiled", 0) + 1
    if save_on_miss:
        try:
            bundle.save_program(name, signature, compiled)
            log_info(f"aot: saved program {name!r} to bundle {bundle_dir!r}")
        except Exception as exc:
            # an unwritable bundle location must not fail training
            log_warning(f"aot: could not save {name!r} to "
                        f"{bundle_dir!r}: {exc!r}")
    return compiled, False
