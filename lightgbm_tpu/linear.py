"""Linear leaves: per-leaf linear models fit on the branch features.

TPU-native equivalent of the reference LinearTreeLearner
(src/treelearner/linear_tree_learner.cpp:123-125 CalculateLinear): the
reference accumulates per-leaf X^T.H.X / X^T.g with OpenMP and solves each
leaf with vendored Eigen; here ALL leaves are accumulated in one pass
(segment-sum of per-row outer products, MXU/VPU friendly) and solved as one
batched ``jnp.linalg.solve`` — with the same numerical-failure fallback to
the constant leaf.

Model semantics mirror the reference: output = leaf_const + sum coeff*x over
the leaf's branch features; rows with NaN in any used feature fall back to
the constant ``leaf_value`` (linear_tree_learner's HAS_NAN path, tree.h
AddPredictionToScore<true>).
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["fit_linear_leaves"]


@functools.partial(jax.jit, static_argnames=("num_leaves",))
def _fit(X, row_leaf, leaf_feats, feat_mask, grad, hess, lam,
         num_leaves: int):
    """Batched per-leaf weighted least squares.

    X: [N, F] raw f32; leaf_feats: [L, K] int32 (0-padded);
    feat_mask: [L, K] f32 1/0; returns (beta [L, K+1], ok [L], row_out [N],
    row_nan [N])."""
    n = X.shape[0]
    L = num_leaves
    k = leaf_feats.shape[1]

    rf = leaf_feats[row_leaf]                    # [N, K]
    fm = feat_mask[row_leaf]                     # [N, K]
    Xr = jnp.take_along_axis(X, rf, axis=1)      # [N, K]
    row_nan = jnp.any(jnp.isnan(Xr) * (fm > 0), axis=1)
    Xr = jnp.nan_to_num(Xr) * fm
    Xa = jnp.concatenate([Xr, jnp.ones((n, 1), Xr.dtype)], axis=1)  # [N,K+1]

    w = jnp.where(row_nan, 0.0, hess)
    g = jnp.where(row_nan, 0.0, grad)
    outer = (Xa[:, :, None] * Xa[:, None, :]) * w[:, None, None]
    XtHX = jax.ops.segment_sum(outer.reshape(n, -1), row_leaf,
                               num_segments=L).reshape(L, k + 1, k + 1)
    Xtg = jax.ops.segment_sum(Xa * g[:, None], row_leaf, num_segments=L)

    # ridge on feature rows only (reference adds linear_lambda to the
    # coefficient block, keeping the constant unpenalized); padded feature
    # rows are replaced by identity rows so the batched solve stays
    # well-posed for every leaf
    eye = jnp.eye(k + 1)
    diag_mask = feat_mask_ext(feat_mask)                    # [L, K+1]
    A = XtHX * diag_mask[:, :, None] * diag_mask[:, None, :]
    ridge = jnp.concatenate([jnp.full((k,), lam), jnp.zeros((1,))])
    A = A + jnp.diag(ridge)[None]
    pad = 1.0 - diag_mask                                   # [L, K+1]
    A = A + jnp.einsum("lk,kj->lkj", pad, eye)

    beta = jnp.linalg.solve(A, -Xtg[..., None])[..., 0]     # [L, K+1]
    ok = jnp.all(jnp.isfinite(beta), axis=1)
    # needs enough data for a stable fit (reference skips leaves whose
    # hessian mass is tiny)
    hsum = jax.ops.segment_sum(w, row_leaf, num_segments=L)
    ok = ok & (hsum > 1e-3)
    beta = jnp.where(ok[:, None], beta, 0.0) * feat_mask_ext(feat_mask)

    row_out = (Xa * beta[row_leaf]).sum(axis=1)             # [N]
    return beta, ok, row_out, row_nan


def feat_mask_ext(feat_mask):
    L = feat_mask.shape[0]
    return jnp.concatenate([feat_mask, jnp.ones((L, 1))], axis=1)


def fit_linear_leaves(tree, row_leaf, X_dev, grad, hess,
                      linear_lambda: float) -> Tuple[np.ndarray, jnp.ndarray]:
    """Fit all leaves of a freshly-grown tree; mutates `tree` with the
    linear model and returns per-row outputs for the train-score update.

    Returns (row_out [N] device array incl. constant fallback rows)."""
    nl = tree.num_leaves
    ni = nl - 1
    parent = np.full(max(ni, 1), -1, np.int32)
    for p in range(ni):
        for c in (tree.left_child[p], tree.right_child[p]):
            if c >= 0:
                parent[c] = p
    # branch features per leaf (reference GetPathToLeaf): unique split
    # features on the root->leaf path, in first-use order
    feats: List[List[int]] = [[] for _ in range(nl)]
    for leaf in range(nl):
        node = tree.leaf_parent[leaf]
        path = []
        while node >= 0:
            f = int(tree.split_feature[node])
            if f not in path:
                path.append(f)
            node = parent[node]
        feats[leaf] = path
    K = max(1, max(len(p) for p in feats))
    leaf_feats = np.zeros((nl, K), np.int32)
    fmask = np.zeros((nl, K), np.float32)
    for leaf, p in enumerate(feats):
        leaf_feats[leaf, :len(p)] = p
        fmask[leaf, :len(p)] = 1.0

    beta, ok, row_out, row_nan = _fit(
        X_dev, row_leaf, jnp.asarray(leaf_feats), jnp.asarray(fmask),
        grad, hess, jnp.float32(linear_lambda), nl)
    beta = np.asarray(beta, np.float64)
    ok = np.asarray(ok)

    tree.is_linear = True
    tree.leaf_const = np.zeros(tree.max_leaves)
    tree.leaf_features = [[] for _ in range(tree.max_leaves)]
    tree.leaf_coeff = [[] for _ in range(tree.max_leaves)]
    for leaf in range(nl):
        if ok[leaf]:
            kf = len(feats[leaf])
            tree.leaf_const[leaf] = beta[leaf, K]
            tree.leaf_features[leaf] = list(feats[leaf])
            tree.leaf_coeff[leaf] = [float(b) for b in beta[leaf, :kf]]
        else:
            # numerical-failure fallback: constant leaf
            tree.leaf_const[leaf] = tree.leaf_value[leaf]

    ok_dev = jnp.asarray(ok)
    leaf_vals = jnp.asarray(tree.leaf_value[:nl], jnp.float32)
    lv_row = leaf_vals[jnp.clip(row_leaf, 0, nl - 1)]
    use_const = row_nan | ~ok_dev[jnp.clip(row_leaf, 0, nl - 1)]
    return jnp.where(use_const, lv_row, row_out)


