# R bindings for lightgbm_tpu — surface of the reference R-package
# (R-package/R/lgb.Dataset.R, lgb.train.R:51, lgb.Booster.R) over the
# C ABI.  Load order: dyn.load the glue built from src/lightgbm_tpu_R.c
# (which links c_api/lib_lightgbm_tpu.so).

#' Construct a Dataset (reference lgb.Dataset, lgb.Dataset.R)
#' @param data numeric matrix [n, f]
#' @param label numeric response vector
#' @param params named list of dataset parameters (max_bin, ...)
lgb.Dataset <- function(data, label = NULL, params = list()) {
  data <- as.matrix(data)
  storage.mode(data) <- "double"
  handle <- .Call("LGBM_R_DatasetCreate", data, nrow(data), ncol(data),
                  .lgb.params.str(params))
  ds <- list(handle = handle, dim = dim(data))
  class(ds) <- "lgb.Dataset"
  if (!is.null(label)) {
    lgb.Dataset.set.label(ds, label)
  }
  ds
}

#' Attach the label field (reference setinfo / lgb.Dataset.set.label)
lgb.Dataset.set.label <- function(dataset, label) {
  .Call("LGBM_R_DatasetSetLabel", dataset$handle, as.double(label))
  invisible(dataset)
}

#' Train a model (reference lgb.train, lgb.train.R:51)
#' @param params named list (objective, num_leaves, ...)
#' @param data an lgb.Dataset
#' @param nrounds number of boosting iterations
lgb.train <- function(params = list(), data, nrounds = 100L) {
  stopifnot(inherits(data, "lgb.Dataset"))
  handle <- .Call("LGBM_R_BoosterCreate", data$handle,
                  .lgb.params.str(params))
  bst <- list(handle = handle)
  class(bst) <- "lgb.Booster"
  for (i in seq_len(nrounds)) {
    finished <- .Call("LGBM_R_BoosterUpdateOneIter", handle)
    if (isTRUE(finished)) break
  }
  bst
}

#' Predict (reference predict.lgb.Booster: multiclass returns an
#' [nrow, num_class] matrix)
predict.lgb.Booster <- function(object, newdata, rawscore = FALSE,
                                num_iteration = -1L, ...) {
  newdata <- as.matrix(newdata)
  storage.mode(newdata) <- "double"
  out <- .Call("LGBM_R_BoosterPredict", object$handle, newdata,
               nrow(newdata), ncol(newdata), isTRUE(rawscore),
               as.integer(num_iteration))
  # the C payload is row-major [n, k]; the glue tags dim = c(k, n), so
  # transpose to the reference's [n, k] orientation
  if (!is.null(dim(out))) {
    out <- t(out)
  }
  out
}

#' Save the model in the reference text format (reference lgb.save)
lgb.save <- function(booster, filename) {
  .Call("LGBM_R_BoosterSaveModel", booster$handle, filename)
  invisible(booster)
}

#' Load a model file (reference lgb.load)
lgb.load <- function(filename) {
  handle <- .Call("LGBM_R_BoosterLoadModel", filename)
  bst <- list(handle = handle)
  class(bst) <- "lgb.Booster"
  bst
}

#' Number of trained trees
lgb.num.trees <- function(booster) {
  .Call("LGBM_R_BoosterNumTrees", booster$handle)
}

# "k1=v1 k2=v2" serialization (reference lgb.params2str, utils.R)
.lgb.params.str <- function(params) {
  if (length(params) == 0L) return("")
  paste(vapply(names(params), function(k) {
    paste0(k, "=", paste(params[[k]], collapse = ","))
  }, character(1L)), collapse = " ")
}
