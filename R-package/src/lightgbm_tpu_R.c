/* R glue for the lightgbm_tpu C ABI — the role of the reference's
 * R-package/src/lightgbm_R.cpp: SEXP-taking wrappers around the LGBM_*
 * entry points of c_api/lib_lightgbm_tpu.so, registered for .Call().
 *
 * Build (from R-package/): R CMD SHLIB src/lightgbm_tpu_R.c \
 *   -L../c_api -l:lib_lightgbm_tpu.so
 */
#include <R.h>
#include <Rinternals.h>
#include <R_ext/Rdynload.h>

#include <stdint.h>
#include <string.h>

typedef void* DatasetHandle;
typedef void* BoosterHandle;

extern const char* LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                                     int32_t nrow, int32_t ncol,
                                     int is_row_major, const char* parameters,
                                     const DatasetHandle reference,
                                     DatasetHandle* out);
extern int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                                const void* data, int num_element, int type);
extern int LGBM_DatasetFree(DatasetHandle handle);
extern int LGBM_BoosterCreate(const DatasetHandle train_data,
                              const char* parameters, BoosterHandle* out);
extern int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished);
extern int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                                     int data_type, int32_t nrow,
                                     int32_t ncol, int is_row_major,
                                     int predict_type, int start_iteration,
                                     int num_iteration, const char* parameter,
                                     int64_t* out_len, double* out_result);
extern int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                                 int num_iteration,
                                 int feature_importance_type,
                                 const char* filename);
extern int LGBM_BoosterCreateFromModelfile(const char* filename,
                                           int* out_num_iterations,
                                           BoosterHandle* out);
extern int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out);
extern int LGBM_BoosterNumModelPerIteration(BoosterHandle handle, int* out);
extern int LGBM_BoosterFree(BoosterHandle handle);

static void check_call(int rc) {
  if (rc != 0) {
    Rf_error("lightgbm_tpu: %s", LGBM_GetLastError());
  }
}

static void dataset_finalizer(SEXP ptr) {
  DatasetHandle h = R_ExternalPtrAddr(ptr);
  if (h != NULL) {
    LGBM_DatasetFree(h);
    R_ClearExternalPtr(ptr);
  }
}

static void booster_finalizer(SEXP ptr) {
  BoosterHandle h = R_ExternalPtrAddr(ptr);
  if (h != NULL) {
    LGBM_BoosterFree(h);
    R_ClearExternalPtr(ptr);
  }
}

/* data: numeric matrix (column-major in R); params: scalar string */
SEXP LGBM_R_DatasetCreate(SEXP data, SEXP nrow, SEXP ncol, SEXP params) {
  DatasetHandle h = NULL;
  check_call(LGBM_DatasetCreateFromMat(
      REAL(data), 1 /* float64 */, Rf_asInteger(nrow), Rf_asInteger(ncol),
      0 /* column-major */, CHAR(Rf_asChar(params)), NULL, &h));
  SEXP ptr = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ptr, dataset_finalizer, TRUE);
  UNPROTECT(1);
  return ptr;
}

SEXP LGBM_R_DatasetSetLabel(SEXP handle, SEXP label) {
  int n = Rf_length(label);
  float* buf = (float*)R_alloc(n, sizeof(float));
  double* src = REAL(label);
  for (int i = 0; i < n; ++i) buf[i] = (float)src[i];
  check_call(LGBM_DatasetSetField(R_ExternalPtrAddr(handle), "label", buf, n,
                                  0 /* float32 */));
  return R_NilValue;
}

SEXP LGBM_R_BoosterCreate(SEXP train, SEXP params) {
  BoosterHandle h = NULL;
  check_call(LGBM_BoosterCreate(R_ExternalPtrAddr(train),
                                CHAR(Rf_asChar(params)), &h));
  SEXP ptr = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ptr, booster_finalizer, TRUE);
  UNPROTECT(1);
  return ptr;
}

SEXP LGBM_R_BoosterUpdateOneIter(SEXP handle) {
  int fin = 0;
  check_call(LGBM_BoosterUpdateOneIter(R_ExternalPtrAddr(handle), &fin));
  return Rf_ScalarLogical(fin);
}

SEXP LGBM_R_BoosterPredict(SEXP handle, SEXP data, SEXP nrow, SEXP ncol,
                           SEXP rawscore, SEXP num_iteration) {
  int n = Rf_asInteger(nrow);
  /* the predict payload is n * num_class doubles (multiclass models
   * return one column per class) — size the R vector accordingly */
  int k = 1;
  check_call(LGBM_BoosterNumModelPerIteration(R_ExternalPtrAddr(handle), &k));
  if (k < 1) k = 1;
  SEXP out = PROTECT(Rf_allocVector(REALSXP, (R_xlen_t)n * k));
  int64_t out_len = 0;
  check_call(LGBM_BoosterPredictForMat(
      R_ExternalPtrAddr(handle), REAL(data), 1 /* float64 */, n,
      Rf_asInteger(ncol), 0 /* column-major */,
      Rf_asLogical(rawscore) ? 1 : 0, 0, Rf_asInteger(num_iteration), "",
      &out_len, REAL(out)));
  if (out_len != (int64_t)n * k) {
    UNPROTECT(1);
    Rf_error("lightgbm_tpu: predict returned %lld values, expected %lld",
             (long long)out_len, (long long)n * k);
  }
  if (k > 1) {
    /* row-major [n, k] payload -> R matrix attribute for the caller */
    SEXP dim = PROTECT(Rf_allocVector(INTSXP, 2));
    INTEGER(dim)[0] = k;
    INTEGER(dim)[1] = n;
    Rf_setAttrib(out, R_DimSymbol, dim);
    UNPROTECT(1);
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBM_R_BoosterSaveModel(SEXP handle, SEXP filename) {
  check_call(LGBM_BoosterSaveModel(R_ExternalPtrAddr(handle), 0, -1, 0,
                                   CHAR(Rf_asChar(filename))));
  return R_NilValue;
}

SEXP LGBM_R_BoosterLoadModel(SEXP filename) {
  BoosterHandle h = NULL;
  int n_iter = 0;
  check_call(LGBM_BoosterCreateFromModelfile(CHAR(Rf_asChar(filename)),
                                             &n_iter, &h));
  SEXP ptr = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ptr, booster_finalizer, TRUE);
  UNPROTECT(1);
  return ptr;
}

SEXP LGBM_R_BoosterNumTrees(SEXP handle) {
  int n = 0;
  check_call(LGBM_BoosterNumberOfTotalModel(R_ExternalPtrAddr(handle), &n));
  return Rf_ScalarInteger(n);
}

static const R_CallMethodDef call_methods[] = {
    {"LGBM_R_DatasetCreate", (DL_FUNC)&LGBM_R_DatasetCreate, 4},
    {"LGBM_R_DatasetSetLabel", (DL_FUNC)&LGBM_R_DatasetSetLabel, 2},
    {"LGBM_R_BoosterCreate", (DL_FUNC)&LGBM_R_BoosterCreate, 2},
    {"LGBM_R_BoosterUpdateOneIter", (DL_FUNC)&LGBM_R_BoosterUpdateOneIter, 1},
    {"LGBM_R_BoosterPredict", (DL_FUNC)&LGBM_R_BoosterPredict, 6},
    {"LGBM_R_BoosterSaveModel", (DL_FUNC)&LGBM_R_BoosterSaveModel, 2},
    {"LGBM_R_BoosterLoadModel", (DL_FUNC)&LGBM_R_BoosterLoadModel, 1},
    {"LGBM_R_BoosterNumTrees", (DL_FUNC)&LGBM_R_BoosterNumTrees, 1},
    {NULL, NULL, 0}};

void R_init_lightgbm_tpu(DllInfo* dll) {
  R_registerRoutines(dll, NULL, call_methods, NULL, NULL);
  R_useDynamicSymbols(dll, FALSE);
}
