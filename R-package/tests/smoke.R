# Smoke test: train on the reference's binary.train via the C ABI
# (VERDICT r4 #10 done-criterion).  Run from the repo root:
#   cd R-package && R CMD SHLIB src/lightgbm_tpu_R.c -L../c_api \
#     -l:lib_lightgbm_tpu.so && Rscript tests/smoke.R
dyn.load(file.path("src", paste0("lightgbm_tpu_R", .Platform$dynlib.ext)))
source(file.path("R", "lightgbm_tpu.R"))

read_label_first <- function(path, n_features) {
  # binary.train is dense TSV (label first); also handles sparse k:v pairs
  lines <- readLines(path)
  y <- numeric(length(lines))
  X <- matrix(0, nrow = length(lines), ncol = n_features)
  for (i in seq_along(lines)) {
    toks <- strsplit(lines[[i]], "[ \t]+")[[1]]
    toks <- toks[nzchar(toks)]
    y[i] <- as.numeric(toks[[1]])
    rest <- toks[-1]
    if (length(rest) > 0 && grepl(":", rest[[1]], fixed = TRUE)) {
      for (t in rest) {
        kv <- strsplit(t, ":", fixed = TRUE)[[1]]
        X[i, as.integer(kv[[1]]) + 1L] <- as.numeric(kv[[2]])
      }
    } else {
      X[i, seq_along(rest)] <- as.numeric(rest)
    }
  }
  list(X = X, y = y)
}

d <- read_label_first("/root/reference/examples/binary_classification/binary.train", 28)
train <- lgb.Dataset(d$X, label = d$y, params = list(max_bin = 63))
bst <- lgb.train(list(objective = "binary", num_leaves = 15,
                      verbosity = -1), train, nrounds = 10L)
stopifnot(lgb.num.trees(bst) == 10L)
p <- predict(bst, d$X)
auc_ord <- order(p)
pos <- d$y[auc_ord] == 1
auc <- (sum(which(pos)) - sum(pos) * (sum(pos) + 1) / 2) /
  (sum(pos) * sum(!pos))
cat("train AUC:", auc, "\n")
stopifnot(auc > 0.8)
tmp <- tempfile(fileext = ".txt")
lgb.save(bst, tmp)
bst2 <- lgb.load(tmp)
stopifnot(max(abs(predict(bst2, d$X[1:50, ]) - p[1:50])) < 1e-6)
cat("R_SMOKE_OK\n")
