/* JNI glue for the lightgbm_tpu C ABI — the role of the reference's SWIG
 * layer (swig/lightgbmlib.i generates Java wrappers over c_api.h; here the
 * handful of entry points the Java API class needs are hand-written, which
 * is smaller and carries no SWIG build dependency).
 *
 * Build (any JDK; the rpath makes the C ABI library resolvable at load
 * time without LD_LIBRARY_PATH):
 *   gcc -shared -fPIC -I"$JAVA_HOME/include" -I"$JAVA_HOME/include/linux" \
 *       src/lightgbm_tpu_jni.c -L../c_api -l:lib_lightgbm_tpu.so \
 *       -Wl,-rpath,"$(realpath ../c_api)" -o liblightgbm_tpu_jni.so
 */
#include <jni.h>
#include <stdint.h>

typedef void* DatasetHandle;
typedef void* BoosterHandle;

extern const char* LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromMat(const void*, int, int32_t, int32_t, int,
                                     const char*, const DatasetHandle,
                                     DatasetHandle*);
extern int LGBM_DatasetSetField(DatasetHandle, const char*, const void*, int,
                                int);
extern int LGBM_DatasetFree(DatasetHandle);
extern int LGBM_BoosterCreate(const DatasetHandle, const char*,
                              BoosterHandle*);
extern int LGBM_BoosterUpdateOneIter(BoosterHandle, int*);
extern int LGBM_BoosterPredictForMat(BoosterHandle, const void*, int, int32_t,
                                     int32_t, int, int, int, int, const char*,
                                     int64_t*, double*);
extern int LGBM_BoosterSaveModel(BoosterHandle, int, int, int, const char*);
extern int LGBM_BoosterCreateFromModelfile(const char*, int*, BoosterHandle*);
extern int LGBM_BoosterNumberOfTotalModel(BoosterHandle, int*);
extern int LGBM_BoosterNumModelPerIteration(BoosterHandle, int*);
extern int LGBM_BoosterFree(BoosterHandle);

static void throw_last_error(JNIEnv* env) {
  jclass cls = (*env)->FindClass(env, "java/lang/RuntimeException");
  if (cls != NULL) {
    (*env)->ThrowNew(env, cls, LGBM_GetLastError());
  }
}

JNIEXPORT jlong JNICALL
Java_lightgbm_1tpu_Booster_datasetCreate(JNIEnv* env, jclass cls,
                                         jdoubleArray data, jint nrow,
                                         jint ncol, jstring params) {
  jdouble* buf = (*env)->GetDoubleArrayElements(env, data, NULL);
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  DatasetHandle h = NULL;
  int rc = LGBM_DatasetCreateFromMat(buf, 1 /* float64 */, nrow, ncol,
                                     1 /* row-major */, p, NULL, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  (*env)->ReleaseDoubleArrayElements(env, data, buf, JNI_ABORT);
  if (rc != 0) {
    throw_last_error(env);
    return 0;
  }
  return (jlong)(intptr_t)h;
}

JNIEXPORT void JNICALL
Java_lightgbm_1tpu_Booster_datasetSetLabel(JNIEnv* env, jclass cls,
                                           jlong handle, jfloatArray label) {
  jsize n = (*env)->GetArrayLength(env, label);
  jfloat* buf = (*env)->GetFloatArrayElements(env, label, NULL);
  int rc = LGBM_DatasetSetField((DatasetHandle)(intptr_t)handle, "label",
                                buf, (int)n, 0 /* float32 */);
  (*env)->ReleaseFloatArrayElements(env, label, buf, JNI_ABORT);
  if (rc != 0) throw_last_error(env);
}

JNIEXPORT void JNICALL
Java_lightgbm_1tpu_Booster_datasetFree(JNIEnv* env, jclass cls,
                                       jlong handle) {
  LGBM_DatasetFree((DatasetHandle)(intptr_t)handle);
}

JNIEXPORT jlong JNICALL
Java_lightgbm_1tpu_Booster_boosterCreate(JNIEnv* env, jclass cls,
                                         jlong dataset, jstring params) {
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  BoosterHandle h = NULL;
  int rc = LGBM_BoosterCreate((DatasetHandle)(intptr_t)dataset, p, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  if (rc != 0) {
    throw_last_error(env);
    return 0;
  }
  return (jlong)(intptr_t)h;
}

JNIEXPORT jboolean JNICALL
Java_lightgbm_1tpu_Booster_updateOneIter(JNIEnv* env, jclass cls,
                                         jlong handle) {
  int fin = 0;
  if (LGBM_BoosterUpdateOneIter((BoosterHandle)(intptr_t)handle, &fin) != 0) {
    throw_last_error(env);
  }
  return fin ? JNI_TRUE : JNI_FALSE;
}

JNIEXPORT jdoubleArray JNICALL
Java_lightgbm_1tpu_Booster_predictForMat(JNIEnv* env, jclass cls,
                                         jlong handle, jdoubleArray data,
                                         jint nrow, jint ncol,
                                         jboolean rawScore) {
  int k = 1;
  if (LGBM_BoosterNumModelPerIteration((BoosterHandle)(intptr_t)handle, &k)
      != 0) {
    throw_last_error(env);
    return NULL;
  }
  if (k < 1) k = 1;
  long total = (long)nrow * k;
  if (total > 0x7fffffffL) {   /* jsize is jint; refuse instead of wrapping */
    jclass ex = (*env)->FindClass(env, "java/lang/IllegalArgumentException");
    if (ex != NULL) (*env)->ThrowNew(env, ex, "nrow * num_class > 2^31-1");
    return NULL;
  }
  jdoubleArray out = (*env)->NewDoubleArray(env, (jsize)total);
  if (out == NULL) return NULL;          /* OutOfMemoryError pending */
  jdouble* buf = (*env)->GetDoubleArrayElements(env, data, NULL);
  jdouble* obuf = (*env)->GetDoubleArrayElements(env, out, NULL);
  if (buf == NULL || obuf == NULL) {
    if (buf != NULL)
      (*env)->ReleaseDoubleArrayElements(env, data, buf, JNI_ABORT);
    if (obuf != NULL)
      (*env)->ReleaseDoubleArrayElements(env, out, obuf, JNI_ABORT);
    return NULL;                         /* exception pending */
  }
  int64_t out_len = 0;
  int rc = LGBM_BoosterPredictForMat(
      (BoosterHandle)(intptr_t)handle, buf, 1 /* float64 */, nrow, ncol,
      1 /* row-major */, rawScore ? 1 : 0, 0, -1, "", &out_len, obuf);
  (*env)->ReleaseDoubleArrayElements(env, data, buf, JNI_ABORT);
  (*env)->ReleaseDoubleArrayElements(env, out, obuf, 0);
  if (rc != 0) {
    throw_last_error(env);
    return NULL;
  }
  return out;
}

JNIEXPORT void JNICALL
Java_lightgbm_1tpu_Booster_saveModel(JNIEnv* env, jclass cls, jlong handle,
                                     jstring filename) {
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  int rc = LGBM_BoosterSaveModel((BoosterHandle)(intptr_t)handle, 0, -1, 0, f);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  if (rc != 0) throw_last_error(env);
}

JNIEXPORT jlong JNICALL
Java_lightgbm_1tpu_Booster_loadModel(JNIEnv* env, jclass cls,
                                     jstring filename) {
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  BoosterHandle h = NULL;
  int n_iter = 0;
  int rc = LGBM_BoosterCreateFromModelfile(f, &n_iter, &h);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  if (rc != 0) {
    throw_last_error(env);
    return 0;
  }
  return (jlong)(intptr_t)h;
}

JNIEXPORT jint JNICALL
Java_lightgbm_1tpu_Booster_numTotalModel(JNIEnv* env, jclass cls,
                                         jlong handle) {
  int n = 0;
  if (LGBM_BoosterNumberOfTotalModel((BoosterHandle)(intptr_t)handle, &n)
      != 0) {
    throw_last_error(env);
  }
  return n;
}

JNIEXPORT void JNICALL
Java_lightgbm_1tpu_Booster_boosterFree(JNIEnv* env, jclass cls,
                                       jlong handle) {
  LGBM_BoosterFree((BoosterHandle)(intptr_t)handle);
}
