package lightgbm_tpu;

/**
 * Java interface to the lightgbm_tpu framework — the role the reference
 * fills with SWIG-generated wrappers (swig/lightgbmlib.i): train, predict,
 * save and load over the stable C ABI (c_api/lib_lightgbm_tpu.so).
 *
 * Usage:
 * <pre>
 *   long ds = Booster.datasetCreate(flatRowMajorX, nrow, ncol, "max_bin=63");
 *   Booster.datasetSetLabel(ds, labels);
 *   long bst = Booster.boosterCreate(ds, "objective=binary num_leaves=15");
 *   for (int i = 0; i &lt; 10; i++) Booster.updateOneIter(bst);
 *   double[] preds = Booster.predictForMat(bst, flatRowMajorX, nrow, ncol,
 *                                          false);
 *   Booster.saveModel(bst, "model.txt");   // reference text format
 * </pre>
 *
 * Build: compile src/lightgbm_tpu_jni.c against any JDK (see its header)
 * and {@code System.loadLibrary("lightgbm_tpu_jni")}.
 */
public final class Booster {
    static {
        System.loadLibrary("lightgbm_tpu_jni");
    }

    private Booster() {}

    /** LGBM_DatasetCreateFromMat over a row-major float64 matrix. */
    public static native long datasetCreate(double[] data, int nrow,
                                            int ncol, String params);

    /** LGBM_DatasetSetField("label"). */
    public static native void datasetSetLabel(long dataset, float[] label);

    /** LGBM_DatasetFree. */
    public static native void datasetFree(long dataset);

    /** LGBM_BoosterCreate. */
    public static native long boosterCreate(long dataset, String params);

    /** LGBM_BoosterUpdateOneIter; returns true when no further splits. */
    public static native boolean updateOneIter(long booster);

    /**
     * LGBM_BoosterPredictForMat; returns nrow values (nrow * numClass for
     * multiclass models, class-minor).
     */
    public static native double[] predictForMat(long booster, double[] data,
                                                int nrow, int ncol,
                                                boolean rawScore);

    /** LGBM_BoosterSaveModel (reference-compatible model text). */
    public static native void saveModel(long booster, String filename);

    /** LGBM_BoosterCreateFromModelfile. */
    public static native long loadModel(String filename);

    /** LGBM_BoosterNumberOfTotalModel. */
    public static native int numTotalModel(long booster);

    /** LGBM_BoosterFree. */
    public static native void boosterFree(long booster);
}
